"""Declarative SLOs over the Watchtower telemetry store, with burn
windows and bounded alerting.

Each ``SloSpec`` names one signal the store can compute per worker, a
ceiling, and a *burn window*: the signal must sit above the ceiling
continuously for the full window before an alert fires.  That is the
standard burn-rate shape — a single bad sample (one slow compile, one
unknown verdict) is noise; the same signal pinned above the ceiling for
several push intervals is an incident.  One alert fires per breach
episode: the episode ends (and the spec re-arms) only when a *measured*
sample drops back to or under the ceiling — a sustained breach cannot
flood the ring, and a quiet no-data window mid-breach holds the episode
open rather than silently re-arming it.

Ceilings and windows are env-tunable without code changes —
``JEPSEN_TPU_SLO_<NAME>`` / ``JEPSEN_TPU_SLO_<NAME>_WINDOW_S`` with the
spec name upper-cased (``JEPSEN_TPU_SLO_UNKNOWN_RATE=0.01``) — read at
engine construction; ``set_ceiling`` retunes a live engine (the smoke
uses this to tighten a ceiling mid-run).  Alerts land in three places:
the engine's bounded ring (``GET /alerts``), the flight recorder's
``alert`` category (so a Perfetto export shows the alert instant on the
same axis as the spans that caused it), and the fleet snapshot.

The engine's lock is a leaf (lint/lock_order.py, ``obs-slo``):
``evaluate`` runs on wire reader threads and the fleet heartbeat.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from jepsen_tpu.clock import mono_now
from jepsen_tpu.obs.recorder import RECORDER
from jepsen_tpu.obs.telemetry import TelemetryStore

#: alert ring capacity (per engine)
ALERT_CAPACITY = 256


@dataclass
class SloSpec:
    """One service-level objective: signal, ceiling, burn window."""
    name: str
    ceiling: float
    burn_window_s: float
    unit: str
    description: str
    #: signal extractor: (store, worker, now) -> value or None (no data)
    value_fn: Callable[[TelemetryStore, Any, float], Optional[float]] = \
        field(repr=False, default=None)

    def doc_row(self) -> Dict[str, Any]:
        return {"name": self.name, "ceiling": self.ceiling,
                "burn-window-s": self.burn_window_s, "unit": self.unit,
                "description": self.description}


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def _p99_dispatch_verdict_us(store, worker, now) -> Optional[float]:
    return store.rates(worker).get("p99-dispatch-verdict-us")


def _unknown_rate(store, worker, now) -> Optional[float]:
    return store.rates(worker).get("unknown-rate")


def _compiles_per_1k(store, worker, now) -> Optional[float]:
    return store.rates(worker).get("compiles-per-1k")


def _worker_stale_s(store, worker, now) -> Optional[float]:
    return store.stale_s(worker, now=now)


def _monitor_lag_epochs(store, worker, now) -> Optional[float]:
    return store.rates(worker).get("monitor-lag-epochs")


def default_specs(interval_s: float) -> List[SloSpec]:
    """The shipped SLO set.  Ceilings are deliberately loose for the
    1-core CI world (first-compile dispatches take whole seconds there);
    production deployments tighten them via the env overrides, and the
    smoke tightens them at runtime via ``set_ceiling``."""
    def c(name: str, default: float) -> float:
        return _env_float(f"JEPSEN_TPU_SLO_{name.upper()}", default)

    def w(name: str, default: float) -> float:
        return _env_float(f"JEPSEN_TPU_SLO_{name.upper()}_WINDOW_S", default)

    return [
        SloSpec("p99_dispatch_verdict_us",
                c("p99_dispatch_verdict_us", 30_000_000.0),
                w("p99_dispatch_verdict_us", 0.0), "us",
                "windowed p99 of the dispatch->verdict edge",
                _p99_dispatch_verdict_us),
        SloSpec("unknown_rate",
                c("unknown_rate", 0.5), w("unknown_rate", 0.0), "ratio",
                "windowed unknown verdicts over completed requests",
                _unknown_rate),
        SloSpec("compiles_per_1k",
                c("compiles_per_1k", 500.0), w("compiles_per_1k", 0.0),
                "compiles/1k dispatches",
                "steady-state compile pressure from the newest push",
                _compiles_per_1k),
        SloSpec("worker_stale_s",
                c("worker_stale_s", 0.0), w("worker_stale_s", 0.0), "s",
                "seconds past the 2-missed-intervals staleness threshold",
                _worker_stale_s),
        SloSpec("monitor_lag_epochs",
                c("monitor_lag_epochs", 8.0),
                w("monitor_lag_epochs", max(0.0, 2 * interval_s)),
                "epochs",
                "worst per-stream streaming-monitor lag behind live",
                _monitor_lag_epochs),
    ]


def tenant_slo_specs(slo_config: Dict[str, Dict[str, float]],
                     interval_s: float) -> List[SloSpec]:
    """Per-tenant burn specs from a tenant table's SLO config
    (serve/tenants.py ``slo_config()``): ``p99_us`` and/or
    ``unknown_rate`` ceilings with an optional shared ``window_s``.
    The signals are the tenant cuts of the **fleet pseudo-worker's**
    pushes (TelemetryStore.tenant_rates), so each value_fn answers only
    for worker ``"fleet"`` — per-worker evaluation of a fleet-wide
    tenant signal would fire one duplicate episode per worker."""
    out: List[SloSpec] = []
    for tenant, cfg in sorted(slo_config.items()):
        window = float(cfg.get("window_s", max(0.0, 2 * interval_s)))

        def p99_fn(store, worker, now, _t=tenant):
            if worker != "fleet":
                return None
            return store.tenant_rates("fleet", _t).get(
                "p99-dispatch-verdict-us")

        def unknown_fn(store, worker, now, _t=tenant):
            if worker != "fleet":
                return None
            return store.tenant_rates("fleet", _t).get("unknown-rate")

        if cfg.get("p99_us") is not None:
            out.append(SloSpec(
                f"tenant_p99_us:{tenant}", float(cfg["p99_us"]), window,
                "us", f"tenant {tenant}: windowed p99 of the "
                      "dispatch->verdict edge", p99_fn))
        if cfg.get("unknown_rate") is not None:
            out.append(SloSpec(
                f"tenant_unknown_rate:{tenant}", float(cfg["unknown_rate"]),
                window, "ratio",
                f"tenant {tenant}: windowed unknown verdicts over "
                "completed requests", unknown_fn))
    return out


class SloEngine:
    """Evaluates every spec against every worker the store knows, on
    each push (``evaluate``) and each heartbeat sweep
    (``evaluate_all``), firing one bounded alert per breach episode."""

    def __init__(self, store: TelemetryStore,
                 specs: Optional[List[SloSpec]] = None,
                 alert_capacity: int = ALERT_CAPACITY):
        self.store = store
        self._lock = threading.Lock()
        self._specs = {s.name: s for s in
                       (specs if specs is not None
                        else default_specs(store.interval_s))}
        self._alerts: deque = deque(maxlen=alert_capacity)
        self._fired_total = 0
        # breach bookkeeping per (spec, worker): when the episode began,
        # and whether its alert already fired
        self._breach_t0: Dict[Any, float] = {}
        self._fired: Dict[Any, bool] = {}

    # -- tuning ----------------------------------------------------------------

    def specs(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [s.doc_row() for s in self._specs.values()]

    def add_spec(self, spec: SloSpec) -> None:
        """Register one more spec on a live engine (per-tenant specs
        arrive after construction, when the tenant table is parsed); a
        same-named spec is replaced, its open episodes kept — the next
        evaluation re-judges them against the new ceiling."""
        with self._lock:
            self._specs[spec.name] = spec

    def set_ceiling(self, name: str, ceiling: float,
                    burn_window_s: Optional[float] = None) -> None:
        """Retune a live spec (used by the smoke to inject a breach
        threshold mid-run); unknown names raise KeyError.

        Open breach episodes for the spec are re-evaluated against the
        new ceiling with a freshly measured sample: an episode the new
        ceiling puts back in-SLO closes (and re-arms) immediately rather
        than waiting for the next push, while a still-breaching episode
        keeps its ``fired`` state — a retune never double-fires."""
        with self._lock:
            spec = self._specs[name]
            spec.ceiling = float(ceiling)
            if burn_window_s is not None:
                spec.burn_window_s = float(burn_window_s)
            open_workers = [w for (n, w) in self._breach_t0 if n == name]
        now = mono_now()
        for worker in open_workers:
            if spec.value_fn is None:
                continue
            try:
                value = spec.value_fn(self.store, worker, now)
            except Exception:  # noqa: BLE001 — a torn push holds the
                continue       # episode open, same as evaluate
            if value is not None and value <= spec.ceiling:
                with self._lock:
                    self._breach_t0.pop((name, worker), None)
                    self._fired.pop((name, worker), None)

    # -- evaluation ------------------------------------------------------------

    def evaluate(self, worker: Any, now: Optional[float] = None,
                 ) -> List[Dict[str, Any]]:
        """Check every spec against one worker; returns alerts fired by
        this call (usually empty)."""
        now = mono_now() if now is None else now
        fired: List[Dict[str, Any]] = []
        with self._lock:
            specs = list(self._specs.values())
        for spec in specs:
            if spec.value_fn is None:
                continue
            try:
                value = spec.value_fn(self.store, worker, now)
            except Exception:  # noqa: BLE001 — a torn push must not
                continue       # poison the evaluation loop
            alert = self._check(spec, worker, value, now)
            if alert is not None:
                fired.append(alert)
        return fired

    def evaluate_all(self, now: Optional[float] = None,
                     ) -> List[Dict[str, Any]]:
        """One sweep over every known worker — the heartbeat-driven path
        that catches staleness (a stale worker, by definition, delivers
        no push to trigger ``evaluate`` for it)."""
        now = mono_now() if now is None else now
        fired: List[Dict[str, Any]] = []
        for w in self.store.workers():
            fired.extend(self.evaluate(w, now=now))
        return fired

    def _check(self, spec: SloSpec, worker: Any, value: Optional[float],
               now: float) -> Optional[Dict[str, Any]]:
        key = (spec.name, worker)
        if value is None:
            # no data is not recovery: a quiet window during a breach
            # must not end the episode (and re-arm the alert) — only a
            # measured in-SLO sample does
            return None
        if value <= spec.ceiling:
            with self._lock:
                self._breach_t0.pop(key, None)
                self._fired.pop(key, None)
            return None
        with self._lock:
            t0 = self._breach_t0.setdefault(key, now)
            if now - t0 < spec.burn_window_s or self._fired.get(key):
                return None
            self._fired[key] = True
            self._fired_total += 1
            alert = {"slo": spec.name, "worker": str(worker),
                     "value": round(float(value), 6),
                     "ceiling": spec.ceiling,
                     "burn-window-s": spec.burn_window_s,
                     "breach-age-s": round(now - t0, 3),
                     "t": round(now, 6), "unit": spec.unit}
            self._alerts.append(alert)
        RECORDER.record("alert", f"slo:{spec.name}:{worker}",
                        args=dict(alert))
        return alert

    def forget(self, worker: Any) -> None:
        """Close every open breach episode for an evicted worker.  The
        registry removed it, so ``worker_stale_s`` (and everything else)
        can never measure an in-SLO sample to re-arm on — without this
        the episode would stay open forever against a ghost."""
        with self._lock:
            for key in [k for k in self._breach_t0 if k[1] == worker]:
                self._breach_t0.pop(key, None)
            for key in [k for k in self._fired if k[1] == worker]:
                self._fired.pop(key, None)

    # -- export ----------------------------------------------------------------

    def alerts(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(a) for a in self._alerts]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"fired-total": self._fired_total,
                    "active-breaches": sorted(
                        f"{name}:{worker}"
                        for (name, worker), on in self._fired.items() if on),
                    "alerts": [dict(a) for a in self._alerts],
                    "specs": [s.doc_row() for s in self._specs.values()]}
