"""Trace context: ids, wall anchors, and the Chrome trace-event export.

A trace is one causal tree per submitted request.  The root ``Request``
mints a 16-hex ``trace-id`` and an 8-hex root ``span-id`` at submit;
every hop (fleet -> wire client -> worker process) creates a child
request that adopts the trace-id and records the sender's span-id as
its ``parent-span-id``.  Spans themselves stay what they always were —
relative monotonic seconds on the *local* clock (monotonic clocks do
not cross process boundaries) — and each request additionally captures
one wall-clock anchor (``anchor-unix-s``) at submit, used only to place
its relative spans on an absolute axis at export time.  Deadline logic
never sees the anchor.

The Chrome trace-event conversion turns a merged trace payload (the
root request's span list plus the ``remote`` payloads absorbed from
worker-side requests) into a ``{"traceEvents": [...]}`` document that
loads directly in Perfetto / ``chrome://tracing``: one duration ("X")
event per lifecycle edge, grouped by the originating pid so a hedge
that crossed processes renders as parallel tracks under one tree.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Iterable, List, Optional

from jepsen_tpu.atomic_io import atomic_write

#: wire field names for the propagated context (SUBMIT frames and the
#: ``serve`` section of results)
CTX_TRACE = "trace-id"
CTX_PARENT = "parent-span-id"


def new_trace_id() -> str:
    """A fresh 16-hex trace id (64 random bits)."""
    return os.urandom(8).hex()


def new_span_id() -> str:
    """A fresh 8-hex span id (32 random bits)."""
    return os.urandom(4).hex()


def wall_anchor() -> float:
    """One wall-clock reading, captured at submit and carried only in
    trace payloads — never compared against deadlines or intervals."""
    return time.time()  # lint: disable=CONC01(user-facing wall clock)


def make_context(trace_id: str, parent_span_id: str) -> Dict[str, str]:
    """The wire form of a trace context, as shipped on SUBMIT frames."""
    return {CTX_TRACE: trace_id, CTX_PARENT: parent_span_id}


def parse_context(ctx: Any) -> Dict[str, Optional[str]]:
    """Tolerant read of a wire context: unknown/garbage fields degrade
    to a fresh root rather than poisoning the receiver."""
    if not isinstance(ctx, dict):
        return {CTX_TRACE: None, CTX_PARENT: None}
    tid = ctx.get(CTX_TRACE)
    par = ctx.get(CTX_PARENT)
    return {CTX_TRACE: tid if isinstance(tid, str) and tid else None,
            CTX_PARENT: par if isinstance(par, str) and par else None}


# -- Chrome trace-event conversion --------------------------------------------

def _payload_events(payload: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Duration events for one request payload's span list, placed on
    the absolute axis by that payload's own wall anchor."""
    anchor = payload.get("anchor-unix-s")
    spans = payload.get("spans") or []
    if anchor is None or not spans:
        return []
    pid = payload.get("pid", 0)
    tid = payload.get("request-id", 0)
    try:
        tid = int(tid)
    except (TypeError, ValueError):
        tid = 0
    args = {"trace-id": payload.get("trace-id"),
            "span-id": payload.get("span-id"),
            "parent-span-id": payload.get("parent-span-id"),
            "request-id": payload.get("request-id")}
    out: List[Dict[str, Any]] = []
    ordered = sorted((s for s in spans if "t" in s and "span" in s),
                     key=lambda s: s["t"])
    for cur, nxt in zip(ordered, ordered[1:]):
        ts_us = (anchor + cur["t"]) * 1e6
        dur_us = max((nxt["t"] - cur["t"]) * 1e6, 1.0)
        out.append({"name": f"{cur['span']}->{nxt['span']}",
                    "cat": "request", "ph": "X",
                    "ts": round(ts_us, 3), "dur": round(dur_us, 3),
                    "pid": pid, "tid": tid, "args": args})
    return out


def chrome_events_from_trace(trace: Dict[str, Any]) -> List[Dict[str, Any]]:
    """All duration events for a merged trace payload: the root
    request's spans plus every absorbed ``remote`` worker payload."""
    events = _payload_events(trace)
    for remote in trace.get("remote") or []:
        if isinstance(remote, dict):
            events.extend(_payload_events(remote))
    return events


def chrome_document(events: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """The trace-event JSON object format Perfetto ingests."""
    return {"traceEvents": list(events), "displayTimeUnit": "ms"}


def write_chrome(path: str, events: Iterable[Dict[str, Any]]) -> str:
    """Atomically write a trace-event document; returns the path."""
    doc = chrome_document(events)
    atomic_write(path, lambda f: json.dump(doc, f, separators=(",", ":")))
    return path
