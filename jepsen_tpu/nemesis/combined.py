"""Combined nemesis packages — nemesis + generator pairs that compose.

Parity: jepsen.nemesis.combined (jepsen/src/jepsen/nemesis/combined.clj):
a *package* bundles a nemesis, the generator that drives it, a final
(healing) generator, and perf-plot metadata; packages compose into one
nemesis + one interleaved fault schedule (compose-packages at
combined.clj:383, nemesis-package one-stop at 407).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from jepsen_tpu import generator as gen
from jepsen_tpu import nemesis as jnemesis
from jepsen_tpu.nemesis import Nemesis
from jepsen_tpu.nemesis.faults import KillNemesis, PauseNemesis
from jepsen_tpu.nemesis.partition import (PacketNemesis, Partitioner,
                                          random_halves_grudge)
from jepsen_tpu.nemesis.time import ClockNemesis, clock_gen
from jepsen_tpu import net as jnet

DEFAULT_INTERVAL = 10.0  # seconds between fault transitions
                          # (combined.clj default-interval)


@dataclass
class Package:
    nemesis: Optional[Nemesis] = None
    generator: Any = None
    final_generator: Any = None
    perf: List[Dict[str, Any]] = field(default_factory=list)


def _cycle_ops(interval, *ops):
    """start/stop loop with the package interval."""
    return gen.stagger(interval, gen.cycle(gen.lift(list(ops))))


def db_package(opts: Optional[Dict] = None) -> Package:
    """Kill/pause faults via DB capabilities (combined.clj:142)."""
    opts = opts or {}
    interval = opts.get("interval", DEFAULT_INTERVAL)
    faults = set(opts.get("faults", ["kill", "pause"]))
    members, gens, finals, perf = [], [], [], []
    if "kill" in faults:
        members.append(KillNemesis())
        gens.append(_cycle_ops(
            interval,
            {"f": "kill", "type": "info",
             "value": opts.get("targets", "one")},
            {"f": "start", "type": "info"}))
        finals.append({"f": "start", "type": "info"})
        perf.append({"name": "kill", "start": ["kill"], "stop": ["start"],
                     "color": "#E9A4A0"})
    if "pause" in faults:
        members.append(PauseNemesis())
        gens.append(_cycle_ops(
            interval,
            {"f": "pause", "type": "info",
             "value": opts.get("targets", "one")},
            {"f": "resume", "type": "info"}))
        finals.append({"f": "resume", "type": "info"})
        perf.append({"name": "pause", "start": ["pause"], "stop": ["resume"],
                     "color": "#C5A0E9"})
    return Package(nemesis=jnemesis.compose(members) if members else None,
                   generator=gen.mix(gens) if gens else None,
                   final_generator=finals or None,
                   perf=perf)


def random_grudge(nodes):
    """Default partition shape mix (combined.clj:227's targets)."""
    kind = random.choice(["halves", "one", "majorities-ring"])
    if kind == "halves":
        return random_halves_grudge(nodes)
    if kind == "one":
        return jnet.complete_grudge(
            jnet.split_one(random.choice(list(nodes)), nodes))
    return jnet.majorities_ring(nodes)


def partition_package(opts: Optional[Dict] = None) -> Package:
    """Network partition faults (combined.clj:227)."""
    opts = opts or {}
    interval = opts.get("interval", DEFAULT_INTERVAL)

    nem = Partitioner(opts.get("grudge_fn", random_grudge))
    g = _cycle_ops(interval,
                   {"f": "start-partition", "type": "info"},
                   {"f": "stop-partition", "type": "info"})
    return Package(nemesis=nem, generator=g,
                   final_generator=[{"f": "stop-partition", "type": "info"}],
                   perf=[{"name": "partition", "start": ["start-partition"],
                          "stop": ["stop-partition"], "color": "#E9DCA0"}])


def partition_hold_package(opts: Optional[Dict] = None) -> Package:
    """ONE partition, started after ``delay`` seconds and held until the
    final heal — the deterministic schedule for refutation tests: a
    bug-catching test must *force* its bug's window (a long, known one),
    not hope a start/stop cycle lands on it.  The grudge fn still decides
    who is severed (e.g. the live-discovered leader)."""
    opts = opts or {}
    nem = Partitioner(opts.get("grudge_fn", random_grudge))
    g = [gen.sleep(float(opts.get("delay", 1.0))),
         gen.once(gen.lift({"f": "start-partition", "type": "info"}))]
    return Package(nemesis=nem, generator=g,
                   final_generator=[{"f": "stop-partition", "type": "info"}],
                   perf=[{"name": "partition", "start": ["start-partition"],
                          "stop": ["stop-partition"], "color": "#E9DCA0"}])


def packet_package(opts: Optional[Dict] = None) -> Package:
    """tc-netem packet faults (combined.clj:285)."""
    opts = opts or {}
    interval = opts.get("interval", DEFAULT_INTERVAL)
    behaviors = opts.get("behaviors", ["slow", "flaky"])
    nem = PacketNemesis()
    g = _cycle_ops(interval,
                   gen.FnGen(lambda: {"f": "start-packet", "type": "info",
                                      "value": random.choice(behaviors)}),
                   {"f": "stop-packet", "type": "info"})
    return Package(nemesis=nem, generator=g,
                   final_generator=[{"f": "stop-packet", "type": "info"}],
                   perf=[{"name": "packet", "start": ["start-packet"],
                          "stop": ["stop-packet"], "color": "#A0E9DB"}])


def clock_package(opts: Optional[Dict] = None) -> Package:
    """Clock skew faults (combined.clj:326)."""
    opts = opts or {}
    interval = opts.get("interval", DEFAULT_INTERVAL)
    nem = ClockNemesis()
    g = gen.stagger(interval, clock_gen())
    return Package(nemesis=nem, generator=g,
                   final_generator=[{"f": "reset-clock", "type": "info",
                                     "value": {"targets": "all"}}],
                   perf=[{"name": "clock", "start": ["bump-clock",
                                                     "strobe-clock"],
                          "stop": ["reset-clock"], "color": "#A0B2E9"}])


def compose_packages(packages: Sequence[Package]) -> Package:
    """Merge packages: composed nemesis, mixed generators, sequential finals
    (combined.clj:383)."""
    ps = [p for p in packages if p.nemesis is not None]
    return Package(
        nemesis=jnemesis.compose([p.nemesis for p in ps]),
        generator=gen.mix([p.generator for p in ps
                           if p.generator is not None]),
        final_generator=[p.final_generator for p in ps
                         if p.final_generator is not None],
        perf=[x for p in ps for x in p.perf])


def nemesis_package(opts: Optional[Dict] = None) -> Package:
    """One-stop construction from a fault list (combined.clj:407):
    faults ⊆ {partition, kill, pause, packet, clock}."""
    opts = opts or {}
    faults = set(opts.get("faults", ["partition"]))
    packages = []
    if faults & {"kill", "pause"}:
        packages.append(db_package({**opts,
                                    "faults": faults & {"kill", "pause"}}))
    if "partition" in faults:
        packages.append(partition_package(opts))
    if "packet" in faults:
        packages.append(packet_package(opts))
    if "clock" in faults:
        packages.append(clock_package(opts))
    return compose_packages(packages)
