"""Clock-skew nemesis — native helpers compiled on the nodes.

Parity: jepsen.nemesis.time (jepsen/src/jepsen/nemesis/time.clj): uploads C
sources (ours: jepsen_tpu/native/bump-time.c, strobe-time.c — independent
implementations) and gcc-compiles them on each node (time.clj:21-51), then
drives clock faults: reset (time.clj:86), bump (92), strobe (98); the
clock nemesis (104) and its generator (204).
"""

from __future__ import annotations

import os
import random
from typing import Any, Dict, List, Optional

from jepsen_tpu.control import on_nodes, session
from jepsen_tpu.history import Op
from jepsen_tpu.nemesis import Nemesis
from jepsen_tpu.nemesis.faults import NATIVE_DIR, pick_nodes
from jepsen_tpu.nemesis.registry import registry_of

REMOTE_DIR = "/opt/jepsen-tpu"


def install_tools(test) -> None:
    """Upload + compile the clock helpers on every node (time.clj:21-51)."""

    def inst(t, node):
        s = session(t, node).sudo()
        s.exec("mkdir", "-p", REMOTE_DIR)
        for name in ("bump-time", "strobe-time", "strobe-time-mono"):
            src = os.path.join(NATIVE_DIR, f"{name}.c")
            session(t, node).upload(src, f"/tmp/{name}.c")
            s.exec("gcc", "-O2", "-o", f"{REMOTE_DIR}/{name}",
                   f"/tmp/{name}.c")

    on_nodes(test, inst)


def reset_time(test, nodes=None) -> None:
    """Resync with NTP or force a sane clock (time.clj:86)."""

    def rt(t, node):
        s = session(t, node).sudo()
        if not s.exec_result("ntpdate", "-p", "1", "-b",
                             "pool.ntp.org").ok:
            s.exec_result("chronyc", "makestep")

    on_nodes(test, rt, nodes)


def bump_time(test, node: str, delta_ms: int) -> None:
    session(test, node).sudo().exec(f"{REMOTE_DIR}/bump-time", str(delta_ms))


def strobe_time(test, node: str, delta_ms: int, period_ms: int,
                duration_ms: int, mono: bool = False) -> None:
    """``mono=True`` uses the monotonic-paced variant (phase-accurate over
    long strobes; the reference's strobe-time-experiment role)."""
    binary = "strobe-time-mono" if mono else "strobe-time"
    session(test, node).sudo().exec(
        f"{REMOTE_DIR}/{binary}", str(delta_ms), str(period_ms),
        str(duration_ms))


class ClockNemesis(Nemesis):
    """Drives :reset / :bump / :strobe clock ops (time.clj:104)."""

    def setup(self, test):
        install_tools(test)
        reset_time(test)
        return self

    def invoke(self, test, op: Op) -> Op:
        v = op.value if isinstance(op.value, dict) else {}
        targets = pick_nodes(test, v.get("targets", "all"))
        if op.f == "reset-clock":
            reset_time(test, targets)
            registry_of(test).resolve(f"clock:{id(self)}")
        elif op.f == "bump-clock":
            registry_of(test).register(
                f"clock:{id(self)}", lambda: reset_time(test),
                "skewed clocks")
            delta = v.get("delta_ms", random.choice(
                [-60_000, -1_000, -250, 250, 1_000, 60_000]))
            for n in targets:
                bump_time(test, n, delta)
        elif op.f == "strobe-clock":
            registry_of(test).register(
                f"clock:{id(self)}", lambda: reset_time(test),
                "strobed clocks")
            for n in targets:
                strobe_time(test, n,
                            v.get("delta_ms", 200),
                            v.get("period_ms", 10),
                            v.get("duration_ms", 1_000))
        else:
            raise ValueError(f"clock nemesis doesn't handle f={op.f!r}")
        return op.with_(type="info", value={"targets": sorted(targets),
                                            **v})

    def teardown(self, test):
        try:
            reset_time(test)
            registry_of(test).resolve(f"clock:{id(self)}")
        except Exception:  # noqa: BLE001
            pass

    def fs(self):
        return ["reset-clock", "bump-clock", "strobe-clock"]


def clock_gen():
    """Mixed clock-fault generator (time.clj:204 clock-gen)."""
    from jepsen_tpu import generator as gen

    def one():
        f = random.choice(["bump-clock", "strobe-clock", "reset-clock"])
        return {"f": f, "type": "info",
                "value": {"targets": random.choice(
                    ["one", "minority", "majority", "all"])}}

    return gen.FnGen(one)
