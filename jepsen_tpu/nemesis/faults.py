"""Process and file fault nemeses: kill, pause, truncate, bitflip.

Parity: jepsen.nemesis's node-start-stopper/hammer-time (nemesis.clj:453-512)
and file corruption (truncate-file nemesis.clj:514, bitflip 550-580 — the
reference downloads a Go binary; ours ships a C++ tool, native/bitflip.cpp,
compiled on the node like the reference compiles its clock helpers).
"""

from __future__ import annotations

import os
import random
from typing import Any, Callable, Dict, List, Optional, Sequence

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu
from jepsen_tpu.history import Op
from jepsen_tpu.nemesis import Nemesis
from jepsen_tpu.nemesis.registry import registry_of

NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")


def pick_nodes(test, spec) -> List[str]:
    """Node-spec language of nemesis/combined.clj:39-62:
    :one / :minority / :majority / :primaries / :all / explicit list."""
    nodes = list(test["nodes"])
    if spec in (None, "one"):
        return [random.choice(nodes)]
    if spec == "minority":
        k = max(1, (len(nodes) - 1) // 2)
        return random.sample(nodes, k)
    if spec == "majority":
        k = len(nodes) // 2 + 1
        return random.sample(nodes, k)
    if spec == "all":
        return nodes
    if spec == "primaries":
        database = test.get("db")
        if isinstance(database, jdb.Primary):
            return list(database.primaries(test)) or [nodes[0]]
        return [nodes[0]]
    if isinstance(spec, (list, tuple)):
        return list(spec)
    return [spec]


class KillNemesis(Nemesis):
    """Kill/restart database processes via the DB's Kill capability
    (nemesis/combined.clj:71-99's db-nemesis)."""

    def invoke(self, test, op: Op) -> Op:
        database = test.get("db")
        if not isinstance(database, jdb.Kill):
            raise RuntimeError("db does not support Kill")
        if op.f == "kill":
            targets = pick_nodes(test, op.value)

            def restart_all():
                for n in test["nodes"]:
                    database.start(test, n)

            registry_of(test).register(f"kill:{id(self)}", restart_all,
                                       "killed db processes")
            for n in targets:
                database.kill(test, n)
            return op.with_(type="info", value=sorted(targets))
        if op.f == "start":
            for n in test["nodes"]:
                database.start(test, n)
            registry_of(test).resolve(f"kill:{id(self)}")
            return op.with_(type="info", value="started")
        raise ValueError(f"kill nemesis doesn't handle f={op.f!r}")

    def fs(self):
        return ["kill", "start"]


class PauseNemesis(Nemesis):
    """SIGSTOP/SIGCONT via the DB's Pause capability (hammer-time,
    nemesis.clj:498)."""

    def invoke(self, test, op: Op) -> Op:
        database = test.get("db")
        if not isinstance(database, jdb.Pause):
            raise RuntimeError("db does not support Pause")
        if op.f == "pause":
            targets = pick_nodes(test, op.value)

            def resume_all():
                for n in test["nodes"]:
                    database.resume(test, n)

            registry_of(test).register(f"pause:{id(self)}", resume_all,
                                       "SIGSTOPped db processes")
            for n in targets:
                database.pause(test, n)
            return op.with_(type="info", value=sorted(targets))
        if op.f == "resume":
            for n in test["nodes"]:
                database.resume(test, n)
            registry_of(test).resolve(f"pause:{id(self)}")
            return op.with_(type="info", value="resumed")
        raise ValueError(f"pause nemesis doesn't handle f={op.f!r}")

    def fs(self):
        return ["pause", "resume"]


class TruncateFile(Nemesis):
    """Chop bytes off the end of a file on some nodes (nemesis.clj:514)."""

    def __init__(self, path: str, bytes_: int = 64):
        self.path = path
        self.bytes_ = bytes_

    def invoke(self, test, op: Op) -> Op:
        if op.f != "truncate":
            raise ValueError(f"truncate nemesis doesn't handle f={op.f!r}")
        targets = pick_nodes(test, op.value)
        for n in targets:
            s = session(test, n).sudo()
            s.exec("truncate", "-s", f"-{self.bytes_}", self.path)
        return op.with_(type="info", value=sorted(targets))

    def fs(self):
        return ["truncate"]


class Bitflip(Nemesis):
    """Flip random bits in a file — ships and compiles native/bitflip.cpp on
    the node (build-on-node, like the reference's clock helpers)."""

    def __init__(self, path: str, probability: float = 1e-3):
        self.path = path
        self.probability = probability
        self._bin: Dict[str, str] = {}

    def _ensure_tool(self, test, node) -> str:
        if node in self._bin:
            return self._bin[node]
        s = session(test, node)
        src = os.path.join(NATIVE_DIR, "bitflip.cpp")
        remote_src = "/tmp/jepsen-bitflip.cpp"
        remote_bin = "/tmp/jepsen-bitflip"
        s.upload(src, remote_src)
        s.exec("g++", "-O2", "-o", remote_bin, remote_src)
        self._bin[node] = remote_bin
        return remote_bin

    def invoke(self, test, op: Op) -> Op:
        if op.f != "bitflip":
            raise ValueError(f"bitflip nemesis doesn't handle f={op.f!r}")
        targets = pick_nodes(test, op.value)
        for n in targets:
            tool = self._ensure_tool(test, n)
            s = session(test, n).sudo()
            s.exec(tool, self.path, str(self.probability))
        return op.with_(type="info", value=sorted(targets))

    def fs(self):
        return ["bitflip"]


class NodeStartStopper(Nemesis):
    """Generic start/stop with user commands (nemesis.clj:453):
    on :start run stop_cmd on targets, on :stop run start_cmd everywhere."""

    def __init__(self, targeter: Callable = None,
                 stop_fn: Callable = None, start_fn: Callable = None):
        self.targeter = targeter or (lambda test, nodes: [random.choice(nodes)])
        self.stop_fn = stop_fn
        self.start_fn = start_fn
        self.affected: List[str] = []

    def invoke(self, test, op: Op) -> Op:
        if op.f == "start":
            targets = self.targeter(test, list(test["nodes"]))

            def restart():
                for n in (self.affected or targets):
                    self.start_fn(test, n)
                self.affected = []

            registry_of(test).register(f"start-stop:{id(self)}", restart,
                                       "stopped nodes")
            for n in targets:
                self.stop_fn(test, n)
            self.affected = targets
            return op.with_(type="info", value=sorted(targets))
        if op.f == "stop":
            for n in (self.affected or test["nodes"]):
                self.start_fn(test, n)
            healed, self.affected = self.affected, []
            registry_of(test).resolve(f"start-stop:{id(self)}")
            return op.with_(type="info", value=sorted(healed))
        raise ValueError(f"start-stopper doesn't handle f={op.f!r}")

    def fs(self):
        return ["start", "stop"]
