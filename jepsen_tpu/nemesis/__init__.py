"""Nemesis protocol and composition — fault injection as a special client.

Parity: jepsen.nemesis (jepsen/src/jepsen/nemesis.clj:12-22): a nemesis is
set up for the whole cluster, receives :info ops from the generator's
nemesis thread, performs faults, and returns completions.  Composition and
f-mapping (nemesis.clj:303-433) let independent fault injectors share the
one nemesis thread.  Network partitioners live in jepsen_tpu.nemesis.partition
(they need the net/control layers); this module is the transport-free core.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Optional, Sequence

from jepsen_tpu.history import Op
from jepsen_tpu.nemesis.registry import (  # noqa: F401
    FaultRegistry, registry_of,
)


class Nemesis:
    def setup(self, test: Dict[str, Any]) -> "Nemesis":
        return self

    def invoke(self, test: Dict[str, Any], op: Op) -> Op:
        raise NotImplementedError

    def teardown(self, test: Dict[str, Any]) -> None:
        pass

    # -- optional Reflection (nemesis.clj:22): which fs this nemesis handles
    def fs(self) -> Optional[Iterable[Any]]:
        return None


class NoopNemesis(Nemesis):
    """Does nothing, usefully (nemesis.clj noop)."""

    def invoke(self, test, op):
        return op.with_(type="info")


noop = NoopNemesis


class FnNemesis(Nemesis):
    """Build a nemesis from a dict of f -> handler(test, op) -> op."""

    def __init__(self, handlers: Dict[Any, Callable],
                 setup_fn: Optional[Callable] = None,
                 teardown_fn: Optional[Callable] = None):
        self.handlers = handlers
        self.setup_fn = setup_fn
        self.teardown_fn = teardown_fn

    def setup(self, test):
        if self.setup_fn:
            self.setup_fn(test)
        return self

    def invoke(self, test, op):
        h = self.handlers.get(op.f)
        if h is None:
            raise ValueError(f"nemesis has no handler for f={op.f!r}")
        return h(test, op)

    def teardown(self, test):
        if self.teardown_fn:
            self.teardown_fn(test)

    def fs(self):
        return list(self.handlers)


class FMap(Nemesis):
    """Rewrite incoming op :f values through a mapping before delegating —
    the dual of generator f_map (nemesis.clj:303)."""

    def __init__(self, fmap: Dict[Any, Any], inner: Nemesis):
        self.fmap = fmap
        self.inv = {v: k for k, v in fmap.items()}
        self.inner = inner

    def setup(self, test):
        self.inner = self.inner.setup(test)
        return self

    def invoke(self, test, op):
        inner_f = self.inv.get(op.f, op.f)
        res = self.inner.invoke(test, op.with_(f=inner_f))
        return res.with_(f=self.fmap.get(res.f, res.f))

    def teardown(self, test):
        self.inner.teardown(test)

    def fs(self):
        inner_fs = self.inner.fs() or []
        return [self.fmap.get(f, f) for f in inner_fs]


def f_map(fmap: Dict[Any, Any], nemesis: Nemesis) -> Nemesis:
    return FMap(fmap, nemesis)


class Compose(Nemesis):
    """Route ops to member nemeses by f (nemesis.clj:385): members declare
    their fs via Reflection, or are given explicit f-sets."""

    def __init__(self, members: Sequence[Nemesis],
                 f_sets: Optional[Sequence[Optional[set]]] = None):
        self.members = list(members)
        self.f_sets = list(f_sets) if f_sets is not None else \
            [set(m.fs() or []) for m in members]

    def setup(self, test):
        self.members = [m.setup(test) for m in self.members]
        return self

    def invoke(self, test, op):
        for m, fs in zip(self.members, self.f_sets):
            if fs is None or op.f in fs:
                return m.invoke(test, op)
        raise ValueError(f"no composed nemesis handles f={op.f!r}")

    def teardown(self, test):
        for m in self.members:
            m.teardown(test)

    def fs(self):
        out = []
        for fs in self.f_sets:
            out.extend(fs or [])
        return out


def compose(members: Sequence[Nemesis]) -> Nemesis:
    return Compose(members)


class ValidatingNemesis(Nemesis):
    """Contract assertions around a nemesis (nemesis.clj:50-91)."""

    def __init__(self, inner: Nemesis):
        self.inner = inner

    def setup(self, test):
        n = self.inner.setup(test)
        if n is None:
            raise RuntimeError("nemesis setup returned None")
        self.inner = n
        return self

    def invoke(self, test, op):
        res = self.inner.invoke(test, op)
        if not isinstance(res, Op):
            raise RuntimeError(f"nemesis returned {res!r}, not an Op")
        return res

    def teardown(self, test):
        self.inner.teardown(test)

    def fs(self):
        return self.inner.fs()


def validate(nemesis: Nemesis) -> Nemesis:
    return ValidatingNemesis(nemesis)
