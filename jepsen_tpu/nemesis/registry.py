"""Fault registry — every injected fault registers an undo; teardown heals.

The reference heals faults in each nemesis's ``teardown!`` (e.g. the
partitioner's heal at nemesis.clj:158-185), which works as long as the
nemesis object survives to teardown and its teardown runs.  Two failure
modes escape that design: a nemesis that *raises mid-fault* (the fault is
live but the nemesis never recorded it), and a generator phase that dies
while a fault is open (teardown may itself need control-plane calls that
the crash skipped).  The registry closes both holes: the *moment* a fault
goes live, its undo closure is registered under a stable key; when the
nemesis heals it normally, it resolves the key; and ``core.run``'s
teardown path invokes every *outstanding* undo — even when the generator
phase raised — so no run exits with the cluster still partitioned, the
clock still skewed, or a process still SIGSTOPped.

Undo closures must be idempotent (healing a healed cluster is a no-op);
heal_all never raises — a failed undo is recorded and the rest still run.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("jepsen.nemesis.registry")


class FaultRegistry:
    """Outstanding-fault ledger for one run.  Keys are stable per fault
    source (re-registering a key replaces its undo — a second
    :start-partition supersedes the first; both heal with one undo)."""

    def __init__(self):
        self._lock = threading.Lock()
        # key -> (undo, description); dict preserves registration order
        self._faults: Dict[str, Tuple[Callable[[], Any], str]] = {}

    def register(self, key: str, undo: Callable[[], Any],
                 description: Optional[str] = None) -> None:
        """Record a live fault.  ``undo`` takes no args and heals it."""
        with self._lock:
            self._faults[key] = (undo, description or key)

    def resolve(self, key: str) -> bool:
        """The nemesis healed this fault itself; drop its undo."""
        with self._lock:
            return self._faults.pop(key, None) is not None

    def outstanding(self) -> List[str]:
        with self._lock:
            return list(self._faults)

    def heal_all(self) -> Dict[str, str]:
        """Invoke every outstanding undo, newest first (LIFO: a fault
        stacked on another unwinds in reverse), collecting outcomes.
        Never raises; clears the ledger."""
        with self._lock:
            items = list(self._faults.items())[::-1]
            self._faults.clear()
        outcomes: Dict[str, str] = {}
        for key, (undo, desc) in items:
            try:
                undo()
                outcomes[key] = "healed"
                logger.info("healed outstanding fault: %s", desc)
            except Exception as e:  # noqa: BLE001 - heal the rest regardless
                outcomes[key] = f"heal failed: {e}"
                logger.exception("healing outstanding fault %s", desc)
        return outcomes


def registry_of(test: Dict[str, Any]) -> FaultRegistry:
    """The run's fault registry, created on first use."""
    reg = test.get("fault_registry")
    if reg is None:
        reg = test["fault_registry"] = FaultRegistry()
    return reg
