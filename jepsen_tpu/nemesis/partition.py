"""Partition nemeses — network splits driven by grudge functions.

Parity: the partitioner family in jepsen.nemesis (nemesis.clj:109-285):
a partitioner nemesis takes a grudge function (nodes -> grudge map), starts
a partition on :start-partition, heals on :stop-partition.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Sequence

from jepsen_tpu import net as jnet
from jepsen_tpu.history import Op
from jepsen_tpu.nemesis import Nemesis
from jepsen_tpu.nemesis.registry import registry_of


def _net_of(test) -> jnet.Net:
    return test.get("net") or jnet.IptablesNet()


class Partitioner(Nemesis):
    """Generic partitioner (nemesis.clj:158-185).  ``grudge_fn(nodes)``
    returns {node: [nodes-to-ignore]}; op values may carry an explicit
    grudge."""

    def __init__(self, grudge_fn: Optional[Callable] = None,
                 start_f="start-partition", stop_f="stop-partition"):
        self.grudge_fn = grudge_fn
        self.start_f = start_f
        self.stop_f = stop_f

    def setup(self, test):
        _net_of(test).heal(test)
        return self

    def invoke(self, test, op: Op) -> Op:
        if op.f == self.start_f:
            grudge = op.value if isinstance(op.value, dict) else \
                (self.grudge_fn(list(test["nodes"])) if self.grudge_fn
                 else None)
            if grudge is None:
                raise ValueError("no grudge to apply")
            # Register the undo BEFORE injecting: if drop_all dies halfway
            # the partition may be partially live, and only the registry
            # guarantees it heals at teardown (registry.py).
            registry_of(test).register(
                f"partition:{id(self)}", lambda: _net_of(test).heal(test),
                "network partition")
            _net_of(test).drop_all(test, grudge)
            return op.with_(type="info",
                            value={n: sorted(v) for n, v in grudge.items()})
        if op.f == self.stop_f:
            _net_of(test).heal(test)
            registry_of(test).resolve(f"partition:{id(self)}")
            return op.with_(type="info", value="network healed")
        raise ValueError(f"partitioner doesn't handle f={op.f!r}")

    def teardown(self, test):
        try:
            _net_of(test).heal(test)
            registry_of(test).resolve(f"partition:{id(self)}")
        except Exception:  # noqa: BLE001
            pass

    def fs(self):
        return [self.start_f, self.stop_f]


def partition_halves() -> Nemesis:
    """Cut the cluster in half (nemesis.clj:186)."""
    return Partitioner(lambda nodes: jnet.complete_grudge(
        jnet.bisect(nodes)))


def random_halves_grudge(nodes):
    """Shuffled bisection grudge — the canonical random-halves partition
    (nemesis.clj:198's shuffle + bisect)."""
    ns = list(nodes)
    random.shuffle(ns)
    return jnet.complete_grudge(jnet.bisect(ns))


def partition_random_halves() -> Nemesis:
    return Partitioner(random_halves_grudge)


def partition_random_node() -> Nemesis:
    """Isolate a random node (nemesis.clj:198)."""
    return Partitioner(lambda nodes: jnet.complete_grudge(
        jnet.split_one(random.choice(list(nodes)), nodes)))


def partition_majorities_ring() -> Nemesis:
    """Intersecting-majorities ring (nemesis.clj:261)."""
    return Partitioner(jnet.majorities_ring)


def bridge_partition() -> Nemesis:
    """Halves connected only via a bridge node (nemesis.clj:145)."""
    return Partitioner(jnet.bridge)


class PacketNemesis(Nemesis):
    """tc-netem packet shaping (the packet-package of
    nemesis/combined.clj:285): :start-packet applies a behavior to target
    nodes, :stop-packet restores."""

    def __init__(self, behaviors: Optional[Dict[str, Dict]] = None):
        self.behaviors = behaviors or {
            "slow": jnet.DEFAULT_SLOW, "flaky": jnet.DEFAULT_FLAKY}

    def invoke(self, test, op: Op) -> Op:
        n = _net_of(test)
        if op.f == "start-packet":
            spec = op.value or {}
            name = spec.get("behavior", "slow") if isinstance(spec, dict) \
                else spec
            nodes = spec.get("targets") if isinstance(spec, dict) else None
            registry_of(test).register(
                f"packet:{id(self)}", lambda: _net_of(test).fast(test),
                "packet shaping")
            n.shape(test, nodes=nodes,
                    behavior=self.behaviors.get(name, jnet.DEFAULT_SLOW))
            return op.with_(type="info")
        if op.f == "stop-packet":
            n.fast(test)
            registry_of(test).resolve(f"packet:{id(self)}")
            return op.with_(type="info")
        raise ValueError(f"packet nemesis doesn't handle f={op.f!r}")

    def teardown(self, test):
        try:
            _net_of(test).fast(test)
            registry_of(test).resolve(f"packet:{id(self)}")
        except Exception:  # noqa: BLE001
            pass

    def fs(self):
        return ["start-packet", "stop-packet"]
