"""Membership nemesis — cluster join/leave churn as a state machine.

Parity: jepsen.nemesis.membership + membership.state
(jepsen/src/jepsen/nemesis/membership.clj:1-60, membership/state.clj:20):
a database-specific :class:`State` answers how to view the cluster from a
node, how to merge node views, which membership ops are possible, and how
to apply/resolve them; the nemesis keeps a merged view fresh by polling and
drives ops from the possible-op stream.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from jepsen_tpu.control import on_nodes
from jepsen_tpu.history import Op
from jepsen_tpu.nemesis import Nemesis


class State:
    """Database-specific membership logic (membership/state.clj:20)."""

    def setup(self, test) -> "State":
        return self

    def node_view(self, test, node) -> Any:
        """This node's view of the cluster (may be None if unreachable)."""
        raise NotImplementedError

    def merge_views(self, test, views: Dict[str, Any]) -> Any:
        """Combine per-node views into one cluster view."""
        raise NotImplementedError

    def possible_ops(self, test, view) -> List[Dict[str, Any]]:
        """Ops the nemesis could do now, e.g. [{'f': 'remove-node', ...}]."""
        raise NotImplementedError

    def apply_op(self, test, view, op: Op) -> Op:
        """Perform a membership change; return the completion op."""
        raise NotImplementedError

    def resolved(self, test, view, op: Op) -> bool:
        """Has this op's effect converged in the view?"""
        return True

    def teardown(self, test) -> None:
        pass


class MembershipNemesis(Nemesis):
    """Polls node views on a background thread; invokes membership ops
    against the current merged view (membership.clj)."""

    def __init__(self, state: State, poll_interval_s: float = 1.0):
        self.state = state
        self.poll_interval_s = poll_interval_s
        self.view: Any = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.pending: List[Op] = []

    # -- view maintenance --------------------------------------------------
    def _refresh(self, test) -> None:
        def nv(t, node):
            try:
                return self.state.node_view(t, node)
            except Exception:  # noqa: BLE001
                return None

        views = on_nodes(test, nv)
        merged = self.state.merge_views(test, views)
        with self._lock:
            self.view = merged
            self.pending = [op for op in self.pending
                            if not self.state.resolved(test, merged, op)]

    def _poll_loop(self, test) -> None:
        while not self._stop.is_set():
            try:
                self._refresh(test)
            except Exception:  # noqa: BLE001
                pass
            self._stop.wait(self.poll_interval_s)

    # -- nemesis protocol --------------------------------------------------
    def setup(self, test):
        self.state = self.state.setup(test)
        self._refresh(test)
        self._thread = threading.Thread(
            target=self._poll_loop, args=(test,), daemon=True,
            name="membership-poll")
        self._thread.start()
        return self

    def invoke(self, test, op: Op) -> Op:
        with self._lock:
            view = self.view
        res = self.state.apply_op(test, view, op)
        with self._lock:
            self.pending.append(res)
        return res

    def teardown(self, test):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        self.state.teardown(test)

    def fs(self):
        return None  # handles whatever the state's possible_ops emit

    # -- generator ---------------------------------------------------------
    def op_stream(self, test):
        """A generator function yielding possible membership ops."""
        import random

        def one():
            with self._lock:
                view = self.view
            ops = self.state.possible_ops(test, view) if view is not None \
                else []
            if not ops:
                return None
            d = dict(random.choice(ops))
            d.setdefault("type", "info")
            return d

        from jepsen_tpu import generator as gen
        return gen.FnGen(one)
