"""DB protocol — installing and managing the database on cluster nodes.

Parity: jepsen.db (jepsen/src/jepsen/db.clj:12-48): setup!/teardown! per
node, with optional capabilities (Kill, Pause, Primary, LogFiles) that the
nemesis packages and log snarfing interrogate.  ``cycle_`` retries
teardown+setup (db.clj:162-199); the tcpdump wrapper captures packets around
another DB (db.clj:88-156).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class DB:
    def setup(self, test: Dict[str, Any], node: str) -> None:
        """Install and start the database on ``node``."""

    def teardown(self, test: Dict[str, Any], node: str) -> None:
        """Stop the database and wipe its state on ``node``."""


class Kill:
    """Capability: start/kill database processes (db.clj:16)."""

    def start(self, test, node) -> None: ...
    def kill(self, test, node) -> None: ...


class Pause:
    """Capability: pause/resume via SIGSTOP/SIGCONT (db.clj:30)."""

    def pause(self, test, node) -> None: ...
    def resume(self, test, node) -> None: ...


class Primary:
    """Capability: primary-aware databases (db.clj:35)."""

    def primaries(self, test) -> List[str]:
        return []

    def setup_primary(self, test, node) -> None: ...


class LogFiles:
    """Capability: which node-side files to download after a run
    (db.clj:44)."""

    def log_files(self, test, node) -> List[str]:
        return []


class NoopDB(DB):
    """No database at all — the in-process testing workhorse."""


noop = NoopDB


class TcpdumpDB(DB, LogFiles):
    """Runs a tcpdump capture from setup to teardown and yields the pcap as
    a log file (db.clj:88-156).  Options:

    - ``ports``: capture only these ports
    - ``clients_only``: filter to traffic from the control node's IP
    - ``filter``: extra pcap filter expression, ANDed in
    """

    DIR = "/tmp/jepsen/tcpdump"

    def __init__(self, ports: Optional[List[int]] = None,
                 clients_only: bool = False,
                 filter: Optional[str] = None):  # noqa: A002 - reference name
        self.ports = list(ports or [])
        self.clients_only = clients_only
        self.filter = filter

    def setup(self, test, node):
        from jepsen_tpu.control import session
        from jepsen_tpu.control import net as cn
        from jepsen_tpu.control import util as cu
        s = session(test, node).sudo()
        s.exec("mkdir", "-p", self.DIR)
        filters = []
        if self.ports:
            filters.append(" or ".join(f"port {p}" for p in self.ports))
        if self.clients_only:
            ip = cn.control_ip(s)
            if ip:
                filters.append(f"host {ip}")
        if self.filter:
            filters.append(self.filter)
        # -U: unbuffered — SIGINT alone leaves the capture half-flushed
        # (db.clj:126-131's observation).
        # Parenthesize each sub-filter: pcap's `and` binds tighter than
        # `or`, so a bare port alternation would swallow the host filter.
        cu.start_daemon(
            s, "/usr/bin/tcpdump",
            "-w", f"{self.DIR}/tcpdump", "-s", "65535", "-B", "16384", "-U",
            " and ".join(f"({f})" for f in filters if f),
            pidfile=f"{self.DIR}/pid", logfile=f"{self.DIR}/log",
            chdir=self.DIR)

    def teardown(self, test, node):
        from jepsen_tpu.control import session
        from jepsen_tpu.control import util as cu
        s = session(test, node).sudo()
        # Clean INT first so tcpdump flushes, then the generic stop + wipe
        # (db.clj:133-151).
        s.exec_result(
            "bash", "-c",
            f"[ -f {self.DIR}/pid ] && kill -INT $(cat {self.DIR}/pid)")
        import time as _time

        from jepsen_tpu.clock import mono_now
        deadline = mono_now() + 5
        while (mono_now() < deadline
               and cu.daemon_running(s, f"{self.DIR}/pid")):
            _time.sleep(0.05)
        cu.stop_daemon(s, f"{self.DIR}/pid")
        s.exec("rm", "-rf", self.DIR)

    def log_files(self, test, node):
        return [f"{self.DIR}/log", f"{self.DIR}/tcpdump"]


tcpdump = TcpdumpDB


def cycle_(db: DB, test: Dict[str, Any], node: str, tries: int = 3) -> None:
    """teardown! then setup!, retrying up to ``tries`` times
    (db.clj:162-199)."""
    last: Optional[Exception] = None
    for _ in range(tries):
        try:
            db.teardown(test, node)
            db.setup(test, node)
            return
        except Exception as e:  # noqa: BLE001 - retry any setup failure
            last = e
    raise RuntimeError(f"db cycle failed after {tries} tries on {node}") from last
