"""DB protocol — installing and managing the database on cluster nodes.

Parity: jepsen.db (jepsen/src/jepsen/db.clj:12-48): setup!/teardown! per
node, with optional capabilities (Kill, Pause, Primary, LogFiles) that the
nemesis packages and log snarfing interrogate.  ``cycle_`` retries
teardown+setup (db.clj:162-199); the tcpdump wrapper captures packets around
another DB (db.clj:88-156).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class DB:
    def setup(self, test: Dict[str, Any], node: str) -> None:
        """Install and start the database on ``node``."""

    def teardown(self, test: Dict[str, Any], node: str) -> None:
        """Stop the database and wipe its state on ``node``."""


class Kill:
    """Capability: start/kill database processes (db.clj:16)."""

    def start(self, test, node) -> None: ...
    def kill(self, test, node) -> None: ...


class Pause:
    """Capability: pause/resume via SIGSTOP/SIGCONT (db.clj:30)."""

    def pause(self, test, node) -> None: ...
    def resume(self, test, node) -> None: ...


class Primary:
    """Capability: primary-aware databases (db.clj:35)."""

    def primaries(self, test) -> List[str]:
        return []

    def setup_primary(self, test, node) -> None: ...


class LogFiles:
    """Capability: which node-side files to download after a run
    (db.clj:44)."""

    def log_files(self, test, node) -> List[str]:
        return []


class NoopDB(DB):
    """No database at all — the in-process testing workhorse."""


noop = NoopDB


def cycle_(db: DB, test: Dict[str, Any], node: str, tries: int = 3) -> None:
    """teardown! then setup!, retrying up to ``tries`` times
    (db.clj:162-199)."""
    last: Optional[Exception] = None
    for _ in range(tries):
        try:
            db.teardown(test, node)
            db.setup(test, node)
            return
        except Exception as e:  # noqa: BLE001 - retry any setup failure
            last = e
    raise RuntimeError(f"db cycle failed after {tries} tries on {node}") from last
