"""Results web browser + checking-service front end.

Parity: jepsen.web (jepsen/src/jepsen/web.clj): an HTTP server listing runs
with validity-colored rows (web.clj:28-36,175), per-run file browsing, and
zip export of a run directory.  Stdlib http.server — no framework needed.

With a serve.CheckService attached (cli.py's ``serve`` command wires one
in), the server additionally exposes the service's observability and
submission surface:

- ``GET /metrics``  — the full metrics snapshot as JSON (counters, queue
  depth, lane occupancy, engine-cache hit/miss/recompile, traces);
- ``GET /metrics.prom`` — the same snapshot in Prometheus text
  exposition (obs/prom.py), pow2 histogram buckets rendered as ``le``
  labels; fleet snapshots add per-worker staleness and alert counters;
- ``GET /alerts``   — the SLO engine's alert ring + spec/breach state
  (obs/slo.py); empty document when no engine is attached;
- ``POST /recorder?on=1|0`` — arm/disarm the flight recorder at
  runtime (fans out to worker processes through a Fleet);
- ``GET /healthz``  — liveness probe: per-worker alive/circuit/queue
  status (the fleet's view with ``--workers N``, a degenerate one-worker
  view for a single service); 503 while no worker can take traffic;
- ``GET /queue``    — a human-readable queue-status page;
- ``GET /trace/<request-id>`` — the merged distributed trace of a
  finished request: one causal tree spanning fleet root, wire clients
  and worker processes (``?perfetto=1`` exports Chrome trace-event
  JSON loadable at ui.perfetto.dev; ``cli.py trace`` talks to this);
- ``POST /submit``  — submit a history for checking: a JSON body with
  ``ops`` (op dicts, the history.jsonl shape) plus the submit options of
  CheckService.submit (kind/model/workload/...); responds with the
  verdict JSON.  This is what ``cli.py submit`` talks to.  A body
  ``tenant`` attributes the request to that tenant (quota, priority,
  per-tenant SLO cut — serve/tenants.py); when per-tenant tokens are
  configured (``JEPSEN_TPU_TENANT_TOKENS``), a tenant-attributed submit
  must present the matching ``X-Tenant-Token`` header — unknown tenant
  or wrong token is a 403, constant-time compare, and the error body
  never echoes token material;
- ``GET /autoscale`` — the Governor's state (serve/autoscale.py):
  policy, decision ring, pending structured scale requests; a null
  document when no autoscaler is attached.
"""

from __future__ import annotations

import html
import json
import os
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import unquote

from jepsen_tpu import store

_COLORS = {True: "#6DB6FE", False: "#FFAA8F", None: "#EEEEEE",
           "unknown": "#FEB95F"}  # validity color scheme


def _index_html(base: str) -> str:
    rows = []
    for r in store.runs(base):
        color = _COLORS.get(r["valid"], _COLORS["unknown"])
        d = html.escape(f"/files/{r['name']}/{r['time']}/")
        z = html.escape(f"/zip/{r['name']}/{r['time']}")
        rows.append(
            f"<tr style='background:{color}'>"
            f"<td><a href='{d}'>{html.escape(r['name'])}</a></td>"
            f"<td>{html.escape(r['time'])}</td>"
            f"<td>{html.escape(str(r['valid']))}</td>"
            f"<td><a href='{z}'>zip</a></td></tr>")
    return ("<html><head><title>jepsen-tpu</title></head><body>"
            "<h1>jepsen-tpu runs</h1>"
            "<table border=1 cellpadding=4 style='border-collapse:collapse'>"
            "<tr><th>test</th><th>time</th><th>valid</th><th>export</th></tr>"
            + "".join(rows) + "</table></body></html>")


def _queue_html(service) -> str:
    snap = service.metrics.snapshot()
    rows = "".join(
        f"<tr><td>{html.escape(str(k))}</td>"
        f"<td>{html.escape(str(v))}</td></tr>"
        for section in ("counters", "gauges", "occupancy", "engine-cache")
        for k, v in snap[section].items())
    traces = []
    for t in reversed(snap["traces"]):
        spans = ", ".join(f"{s['span']}@{s['t']:.3f}s" for s in t["spans"])
        traces.append(f"<tr><td>{t['request-id']}</td>"
                      f"<td>{html.escape(str(t['kind']))}</td>"
                      f"<td>{html.escape(str(t['valid']))}</td>"
                      f"<td>{html.escape(spans)}</td></tr>")
    return ("<html><head><title>jepsen-tpu queue</title></head><body>"
            "<h1>checking-service queue</h1>"
            "<table border=1 cellpadding=4 style='border-collapse:collapse'>"
            "<tr><th>metric</th><th>value</th></tr>" + rows + "</table>"
            "<h2>recent requests</h2>"
            "<table border=1 cellpadding=4 style='border-collapse:collapse'>"
            "<tr><th>id</th><th>kind</th><th>valid</th><th>spans</th></tr>"
            + "".join(traces) + "</table>"
            "<p><a href='/metrics'>metrics JSON</a> · "
            "<a href='/'>runs</a></p></body></html>")


def make_handler(base: str, service=None):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, body: bytes,
                  ctype: str = "text/html; charset=utf-8"):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, code: int, obj):
            self._send(code, json.dumps(obj, default=str).encode(),
                       "application/json")

        def do_GET(self):  # noqa: N802
            path = unquote(self.path)
            if path in ("/", "/index.html"):
                return self._send(200, _index_html(base).encode())
            if path == "/healthz" or path.startswith("/healthz?"):
                # Liveness probe: per-worker status, circuit state, queue
                # depth.  One schema whether a CheckService (degenerate
                # one-worker view) or a Fleet is attached; 503 while no
                # worker can take traffic so a load balancer / the chaos
                # harness can act on the status code alone.
                # ``?deep=1`` additionally interrogates each remote
                # worker over its wire (ProcFleet) — best-effort per
                # worker; services without a deep view ignore it.
                if service is None:
                    return self._send_json(200, {"ok": True, "workers": []})
                if "deep=1" in path:
                    try:
                        hz = service.healthz(deep=True)
                    except TypeError:  # single CheckService: no deep arg
                        hz = service.healthz()
                else:
                    hz = service.healthz()
                return self._send_json(200 if hz.get("ok") else 503, hz)
            if path == "/metrics.prom":
                # The same snapshot in Prometheus text exposition —
                # fleet-shaped snapshots additionally carry per-worker
                # staleness gauges and the SLO alert counter, so one
                # scrape of the fleet endpoint sees the whole plane.
                from jepsen_tpu.obs.prom import render_prom
                if service is None:
                    from jepsen_tpu.engine.cache import engine_cache_stats
                    snap = {"counters": engine_cache_stats()}
                else:
                    snap = service.metrics.snapshot()
                return self._send(
                    200, render_prom(snap).encode(),
                    "text/plain; version=0.0.4; charset=utf-8")
            if path == "/fleet":
                # Fleetport membership (serve/fleetport.py): who is
                # registered, from where, with what mesh, and how much
                # lease each holds.  Secret-free by construction — the
                # document carries an auth-enabled boolean, never any
                # token material.  Fixed fleets (no registry) answer a
                # null membership, not a 404, for uniform polling.
                view = getattr(service, "fleet_view", None)
                if view is not None:
                    return self._send_json(200, view())
                return self._send_json(200, {"registry": None,
                                             "workers": []})
            if path == "/autoscale":
                # Governor state (serve/autoscale.py), reached through
                # the fleet's ``governor`` attribute; services without
                # one answer null, not 404, for uniform polling.
                gov = getattr(service, "governor", None)
                return self._send_json(200, {
                    "governor": gov.snapshot() if gov is not None
                    else None})
            if path == "/alerts":
                # SLO alert ring (obs/slo.py).  Degenerate services with
                # no SLO engine answer an empty document, not a 404 — a
                # dashboard can poll every deployment shape uniformly.
                alerts_fn = getattr(service, "alerts", None)
                slo = getattr(service, "slo", None)
                return self._send_json(200, {
                    "alerts": alerts_fn() if alerts_fn else [],
                    "slo": slo.snapshot() if slo is not None else {}})
            if path == "/metrics":
                if service is None:
                    # Route through the shared engine-cache module, not a
                    # single engine's re-export: "singlev" keys (wgl_tpu)
                    # must show beside "batchv"/"megav" ones.
                    from jepsen_tpu.engine.cache import engine_cache_stats
                    return self._send_json(
                        200, {"engine-cache": engine_cache_stats()})
                return self._send_json(200, service.metrics.snapshot())
            if path.startswith("/trace/"):
                # The merged causal tree for one finished request: root
                # spans from this process plus every worker subtree
                # absorbed off RESULT frames.  ``?perfetto=1`` renders it
                # as a Chrome trace-event document instead (load it at
                # ui.perfetto.dev).
                if service is None:
                    return self._send_json(
                        503, {"error": "no checking service attached"})
                rid, _, query = path[len("/trace/"):].partition("?")
                finder = getattr(service, "merged_trace", None)
                trace = finder(rid) if finder is not None else None
                if trace is None:
                    return self._send_json(
                        404, {"error": f"no trace for request {rid!r}"})
                if "perfetto=1" in query:
                    from jepsen_tpu.obs.trace import (chrome_document,
                                                      chrome_events_from_trace)
                    return self._send_json(
                        200, chrome_document(chrome_events_from_trace(trace)))
                return self._send_json(200, trace)
            if path == "/queue":
                if service is None:
                    return self._send(503, b"no checking service attached")
                return self._send(200, _queue_html(service).encode())
            if path == "/monitor":
                # Live + recent run monitors (jepsen_tpu.monitor): lazy
                # import so the browser never drags the checker stack in.
                from jepsen_tpu.monitor import active_statuses
                return self._send_json(200, {"monitors": active_statuses()})
            if path.startswith("/files/"):
                return self._files(path[len("/files/"):])
            if path.startswith("/zip/"):
                return self._zip(path[len("/zip/"):])
            return self._send(404, b"not found")

        def do_POST(self):  # noqa: N802
            path = unquote(self.path)
            if path == "/recorder" or path.startswith("/recorder?"):
                # Runtime arm/disarm of the flight recorder:
                # ``POST /recorder?on=1`` opens a capture window around a
                # live incident without a restart.  A Fleet fans the
                # toggle out to every worker process; anything else arms
                # the local process ring.
                on = "on=1" in path
                setter = getattr(service, "set_recorder", None)
                if setter is not None:
                    return self._send_json(200, setter(on))
                from jepsen_tpu.obs.recorder import RECORDER
                (RECORDER.enable if on else RECORDER.disable)()
                return self._send_json(
                    200, {"enabled": RECORDER.enabled, **RECORDER.stats()})
            if path != "/submit":
                return self._send(404, b"not found")
            if service is None:
                return self._send_json(
                    503, {"error": "no checking service attached"})
            try:
                n = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(n) or b"{}")
                from jepsen_tpu.history import History, Op
                ops = body.pop("ops")
                hist = History([Op.from_dict(d) for d in ops],
                               reindex=True)
                if body.pop("independent", False):
                    # JSON can't carry the keyed-value tuples of an
                    # independent workload; the client asserts the shape
                    from jepsen_tpu.independent import rewrap_tuples
                    hist = rewrap_tuples(hist)
                timeout = body.pop("timeout_s", None)
            except Exception as e:  # noqa: BLE001
                return self._send_json(400, {"error": f"bad request: {e}"})
            tenant = body.get("tenant")
            if tenant is not None:
                import hmac
                from jepsen_tpu.serve.auth import tenant_tokens
                toks = tenant_tokens()
                if toks:
                    # fail closed: unknown tenant and wrong token are
                    # the same 403, and the body never names which —
                    # nor, ever, any token material
                    expected = toks.get(str(tenant), "")
                    presented = self.headers.get("X-Tenant-Token", "")
                    if not expected or not hmac.compare_digest(
                            presented.encode(), expected.encode()):
                        return self._send_json(
                            403, {"error": "tenant authentication failed"})
            try:
                res = service.check(hist, timeout=timeout, **body)
            except TimeoutError as e:
                return self._send_json(504, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — saturation, bad opts
                return self._send_json(503, {"error": str(e)})
            return self._send_json(200, res)

        def _safe(self, rel: str):
            p = os.path.realpath(os.path.join(base, rel))
            if not p.startswith(os.path.realpath(base)):
                return None
            return p

        def _files(self, rel: str):
            p = self._safe(rel)
            if p is None or not os.path.exists(p):
                return self._send(404, b"not found")
            if os.path.isdir(p):
                entries = sorted(os.listdir(p))
                items = "".join(
                    f"<li><a href='{html.escape(name + ('/' if os.path.isdir(os.path.join(p, name)) else ''))}'>"
                    f"{html.escape(name)}</a></li>" for name in entries)
                return self._send(200, f"<ul>{items}</ul>".encode())
            # Stream the file (run dirs hold pcaps and logs of arbitrary
            # size; never buffer them whole).
            import mimetypes
            ctype = (mimetypes.guess_type(p)[0]
                     or "text/plain; charset=utf-8")
            size = os.path.getsize(p)
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(size))
            self.end_headers()
            try:
                # Cap at the announced length: live files (jepsen.log of a
                # run in progress) grow mid-stream, and extra bytes would
                # desync a keep-alive connection.
                remaining = size
                with open(p, "rb") as f:
                    while remaining > 0:
                        buf = f.read(min(1 << 20, remaining))
                        if not buf:
                            break
                        remaining -= len(buf)
                        self.wfile.write(buf)
                if remaining:  # truncated under us; close() resyncs client
                    self.close_connection = True
            except (BrokenPipeError, ConnectionResetError):
                pass  # client went away mid-download

        def _zip(self, rel: str):
            """Stream a zip of the run dir: ZipFile writes straight into an
            unseekable wrapper over the socket (data-descriptor mode), and
            each member is copied in 1 MiB pieces — a run with gigabytes of
            tcpdump pcaps needs constant memory, not a BytesIO of the whole
            archive (the reference streams too, web.clj:175)."""
            p = self._safe(rel)
            if p is None or not os.path.isdir(p):
                return self._send(404, b"not found")
            self.send_response(200)
            self.send_header("Content-Type", "application/zip")
            # No Content-Length: close-delimited body (HTTP/1.0), so no
            # chunked framing is needed — but the close must then happen.
            self.close_connection = True
            self.end_headers()

            wfile = self.wfile

            class _Unseekable:
                # zipfile probes seek/tell; hiding them selects the
                # streaming (data descriptor) zip variant.
                def write(self, b):
                    wfile.write(b)
                    return len(b)

                def flush(self):
                    wfile.flush()

            try:
                with zipfile.ZipFile(_Unseekable(), "w",
                                     zipfile.ZIP_DEFLATED) as z:
                    for root, _, files in os.walk(p):
                        for fn in sorted(files):
                            full = os.path.join(root, fn)
                            arc = os.path.relpath(full, p)
                            try:
                                src = open(full, "rb")
                            except OSError:
                                continue
                            zi = zipfile.ZipInfo(arc)
                            zi.compress_type = zipfile.ZIP_DEFLATED
                            # force_zip64: sizes are unknown up front in
                            # data-descriptor mode and pcaps can pass 4 GiB
                            with src, z.open(zi, "w",
                                             force_zip64=True) as dst:
                                while True:
                                    buf = src.read(1 << 20)
                                    if not buf:
                                        break
                                    dst.write(buf)
            except (BrokenPipeError, ConnectionResetError):
                pass  # client went away mid-download

    return Handler


def serve(base: str = "store", port: int = 8080, block: bool = True,
          service=None):
    httpd = ThreadingHTTPServer(("0.0.0.0", port),
                                make_handler(base, service=service))
    if block:
        print(f"jepsen-tpu web on http://0.0.0.0:{httpd.server_address[1]}")
        httpd.serve_forever()
    return httpd
