"""Crash-safe file writes: temp file, fsync, atomic rename.

The store's staged durability (store.clj:413-457 — save_0/1/2) only means
anything if each artifact lands *whole*: a run killed mid-``json.dump``
must not leave a torn ``test.json`` shadowing the previous good one, and a
re-analysis (``load_history``) must never see half a ``history.jsonl``.
The classic discipline: write to a temp file in the target directory (same
filesystem, so the final rename is atomic), fsync the data, ``os.replace``
over the destination, then best-effort fsync the directory so the rename
itself survives a power cut.  Readers therefore observe either the old
complete file or the new complete file, never a prefix.
"""

from __future__ import annotations

import contextlib
import os
import tempfile
from typing import Callable, Iterator


def fsync_dir(d: str) -> bool:
    """Durable rename: fsync the directory so the *entry* created by an
    ``os.replace`` survives a power cut, not just the file's data blocks.
    A journal whose rename is still only in the page cache silently
    vanishes on power loss — the fleet would "recover" zero pending cells
    and a monitor resume would fall back cold.  Best-effort (some
    filesystems/platforms refuse directory fds); True = the fsync ran."""
    flags = os.O_RDONLY | getattr(os, "O_DIRECTORY", 0)
    try:
        fd = os.open(d, flags)
    except OSError:
        return False
    try:
        os.fsync(fd)
        return True
    except OSError:
        return False
    finally:
        os.close(fd)


def durable_mkdir(path: str) -> str:
    """``makedirs`` whose directory entries are themselves durable: after
    creating any missing component, fsync its parent so a crash right
    after mkdir can't orphan the files later written inside.  Used for
    fleet journal directories; idempotent.  Returns ``path``."""
    path = os.path.abspath(path)
    missing = []
    p = path
    while p and not os.path.isdir(p):
        missing.append(p)
        parent = os.path.dirname(p)
        if parent == p:
            break
        p = parent
    os.makedirs(path, exist_ok=True)
    for d in reversed(missing):
        fsync_dir(os.path.dirname(d))
    return path


@contextlib.contextmanager
def atomic_path(path: str) -> Iterator[str]:
    """Yield a temp path in ``path``'s directory; on clean exit fsync it
    and rename it over ``path``, on error delete it.  For writers that
    need a *path* rather than a file object (np.savez, format.Writer)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".",
                               suffix=".tmp")
    os.close(fd)
    try:
        yield tmp
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, path)
        fsync_dir(d)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def atomic_write(path: str, write_fn: Callable, mode: str = "w") -> None:
    """Run ``write_fn(file)`` against a temp file, then atomically publish
    it as ``path`` (fsync before rename, directory fsync after)."""
    with atomic_path(path) as tmp:
        with open(tmp, mode) as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())


def exclusive_create(path: str, data: str) -> bool:
    """Create ``path`` with ``data`` iff it does not already exist —
    ``O_CREAT|O_EXCL``, so of N racing creators exactly one returns True
    and the rest see False.  This is the single-winner lock primitive for
    the fleet's journal-recovery claim: unlike ``atomic_write`` (last
    writer wins, by design) the *first* writer wins here and everyone
    else finds out.  The file data and its directory entry are fsynced
    before returning, so a crash after a True cannot resurrect a world
    where nobody held the claim."""
    try:
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644)
    except FileExistsError:
        return False
    try:
        os.write(fd, data.encode("utf-8"))
        os.fsync(fd)
    finally:
        os.close(fd)
    fsync_dir(os.path.dirname(os.path.abspath(path)) or ".")
    return True
