"""Crash-safe file writes: temp file, fsync, atomic rename.

The store's staged durability (store.clj:413-457 — save_0/1/2) only means
anything if each artifact lands *whole*: a run killed mid-``json.dump``
must not leave a torn ``test.json`` shadowing the previous good one, and a
re-analysis (``load_history``) must never see half a ``history.jsonl``.
The classic discipline: write to a temp file in the target directory (same
filesystem, so the final rename is atomic), fsync the data, ``os.replace``
over the destination, then best-effort fsync the directory so the rename
itself survives a power cut.  Readers therefore observe either the old
complete file or the new complete file, never a prefix.
"""

from __future__ import annotations

import contextlib
import os
import tempfile
from typing import Callable, Iterator


def _fsync_dir(d: str) -> None:
    """Durable rename: fsync the directory entry (best-effort — some
    filesystems/platforms refuse O_RDONLY dir fds)."""
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_path(path: str) -> Iterator[str]:
    """Yield a temp path in ``path``'s directory; on clean exit fsync it
    and rename it over ``path``, on error delete it.  For writers that
    need a *path* rather than a file object (np.savez, format.Writer)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".",
                               suffix=".tmp")
    os.close(fd)
    try:
        yield tmp
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, path)
        _fsync_dir(d)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def atomic_write(path: str, write_fn: Callable, mode: str = "w") -> None:
    """Run ``write_fn(file)`` against a temp file, then atomically publish
    it as ``path`` (fsync before rename, directory fsync after)."""
    with atomic_path(path) as tmp:
        with open(tmp, mode) as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
