"""The tpu->cpu fallback chain: a device error never decides a verdict.

A device failure — XLA OOM, runtime wedge, device loss — says nothing
about the *history*, so every engine degrades the affected work to its
host oracle and annotates the verdict with the chain it travelled
(``fallback`` for the winning hop, ``fallback-chain`` for the full
trail).  Only when the host tier is missing or itself gives up does the
verdict degrade to ``unknown`` — and then it says why.  One
implementation of the annotation discipline, consumed by the
linearizable facade, the elle engine's per-group degradation, and the
serve scheduler's host-fallback cells.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

log = logging.getLogger(__name__)


def chain_entry(solver: str, exc: BaseException) -> Dict[str, Any]:
    """One hop of a fallback chain: which solver failed, how."""
    return {"solver": solver, "error": str(exc),
            "error-type": type(exc).__name__}


def annotate_fallback(res: Dict[str, Any], frm: str, to: str,
                      entry: Dict[str, Any],
                      chain: Optional[List[Dict[str, Any]]] = None
                      ) -> Dict[str, Any]:
    """Mark a verdict as produced by the fallback tier: ``fallback``
    names the hop (and the device error that forced it), ``fallback-
    chain`` carries the full trail when there was more than one hop."""
    res["fallback"] = {"from": frm, "to": to,
                       "error": entry["error"],
                       "error-type": entry["error-type"]}
    res["fallback-chain"] = chain if chain is not None else [entry]
    return res


def warn_fallback(frm: str, to: str, exc: BaseException,
                  n_lanes: int = 1) -> None:
    """The operator-facing log line every degradation emits (chains are
    silent failures otherwise — a fleet quietly running on its host
    oracle is a fleet whose device died unnoticed)."""
    log.warning("%s failed (%s: %s); falling back to %s for %d lane(s)",
                frm, type(exc).__name__, exc, to, n_lanes)
