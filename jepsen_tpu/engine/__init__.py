"""engine: the shared device-engine substrate.

Every device checker in this repo — the single-history wgl engine
(checker/wgl_tpu.py), the vmapped batch driver (parallel/batch.py), the
elle cycle engine (elle_tpu/engine.py), the monitor's epoch checkers
(monitor/epochs.py) — answers the same five questions: what shape do I
compile for, where do compiled engines live, how long may I run, what
happens when the device fails, and what evidence must a refutation
carry.  This package owns the one answer to each:

- ``ladder``   — the pow2 bucket/shape ladder (derivations in
  serve/buckets.py; the engine-side shape/chunk/window math here);
- ``cache``    — the bounded LRU compiled-engine cache and its shared
  process-wide instance;
- ``groups``   — lane grouping under the 512-lane vmap cap;
- ``budget``   — Deadline plumbing; exhaustion degrades to ``unknown``;
- ``fallback`` — the tpu->cpu chain; a device error never decides a
  verdict;
- ``witness``  — refutation discipline: device lanes flag, the CPU
  recovers the witness — never a fabricated ``valid: False``;
- ``plugins``  — the drop-in seam: new consistency models register as
  (device kernel, checker name) pairs over the unchanged engine;
  ``opacity`` and ``model_plugin`` are its first consumers.

See docs/engines.md for the contract and the write-a-plugin walkthrough.
"""

from jepsen_tpu.engine.budget import Deadline, exhausted_result  # noqa: F401
from jepsen_tpu.engine.cache import (  # noqa: F401
    CACHE, EngineCache, engine_cache_stats,
)
from jepsen_tpu.engine.fallback import (  # noqa: F401
    annotate_fallback, chain_entry, warn_fallback,
)
from jepsen_tpu.engine.groups import (  # noqa: F401
    MAX_LANES_PER_GROUP, bounded_group_cap, group_slices,
)
from jepsen_tpu.engine.ladder import (  # noqa: F401
    LANE_EVENTS_PER_DISPATCH, batch_chunk, batch_shape, next_capacity,
    round_window,
)
from jepsen_tpu.engine.plugins import (  # noqa: F401
    register_builtin_plugins, register_model_plugin, registered_plugins,
)
from jepsen_tpu.engine.witness import (  # noqa: F401
    WITNESS_BUDGET, cpu_witness, refuted_result,
)
