"""The bounded compiled-engine cache — one LRU for every device engine.

Every device engine in the stack (the single-history wgl driver, the
vmapped batch engine, megabatch's grouped runners) pins jitted
executables whose size scales with window*capacity*chunk; a service that
sees many shapes would grow an unbounded dict without end.  One shared
LRU keeps the hot buckets resident across *all* consumers — the bucket
ladder (serve/buckets.py) bounds the key universe, this cache bounds the
resident set — and its hit/miss/eviction counters feed the serve metrics
endpoint (an eviction storm means the ladder is too fine).

Key discipline: entries key on (tag, model name, model variant, shape
components...), never on closure identity, so every ``get_model()`` call
reuses one compiled engine.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Any, Dict


class EngineCache:
    """Bounded compiled-engine cache (thread-safe LRU)."""

    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        self._d: "OrderedDict[Any, Any]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.group_reuses = 0

    def get(self, key, group_reuse: bool = False):
        """``group_reuse=True`` marks a lookup made for an additional
        dispatch group within ONE logical batch (check_batch's >512-lane
        split, megabatch's grouped vmap): a found entry counts toward
        ``group_reuses`` instead of ``hits``, so the hit rate keeps
        measuring cross-call cache effectiveness rather than being
        inflated by same-dispatch reuse."""
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                if group_reuse:
                    self.group_reuses += 1
                else:
                    self.hits += 1
                return self._d[key]
            self.misses += 1
            return None

    def put(self, key, value):
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.capacity:
                self._d.popitem(last=False)
                self.evictions += 1
            return value

    def __len__(self):
        return len(self._d)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            tags: Dict[str, int] = {}
            for key in self._d:
                tag = key[0] if isinstance(key, tuple) and key else "?"
                tags[str(tag)] = tags.get(str(tag), 0) + 1
            return {"size": len(self._d), "capacity": self.capacity,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "group_reuses": self.group_reuses,
                    "tags": tags}


#: The shared engine cache: batch/single/megabatch runners all live here
#: (distinct key tags), so one knob bounds total pinned executables.
CACHE = EngineCache(int(os.environ.get("JEPSEN_TPU_ENGINE_CACHE", "32")))


def engine_cache_stats() -> Dict[str, Any]:
    """Hit/miss/eviction counters of the compiled-engine cache (a miss is
    a fresh trace+compile — the serve metrics' recompile counter), plus
    a per-tag resident count so the "singlev"/"batchv"/"megav" key
    families are all visible on the metrics surface."""
    return CACHE.stats()
