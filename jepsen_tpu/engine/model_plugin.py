"""The generic device-model plugin checker.

One class turns any registered :class:`~jepsen_tpu.models.base.JaxModel`
into a linearizability checker riding the shared engine substrate: the
model is constructed per check (so shape knobs can derive from the
history, bucketed onto the serve ladder for compile-cache reuse) and
handed to the :class:`~jepsen_tpu.checker.linearizable.Linearizable`
facade, which owns algorithm selection, the tpu->cpu fallback chain, and
witness recovery.  Kept out of :mod:`jepsen_tpu.engine.plugins` so the
registration seam stays import-light (checker.core imports it while
itself mid-import).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from jepsen_tpu.checker.core import Checker
from jepsen_tpu.history import History


class ModelPluginChecker(Checker):
    """Linearizability over a named device model.

    ``derive(history, model_kw) -> extra_kw`` lets a plugin size the
    model from the history (e.g. the fifo queue's ring capacity, bucketed
    pow2 so successive checks share one compiled engine); explicit
    ``model_kw`` entries always win over derived ones.
    """

    def __init__(self, model_name: str,
                 model_kw: Optional[Dict[str, Any]] = None,
                 derive: Optional[Callable[[History, Dict[str, Any]],
                                           Dict[str, Any]]] = None,
                 algorithm: Optional[str] = None, **engine_opts):
        self.model_name = model_name
        self.model_kw = dict(model_kw or {})
        self.derive = derive
        self.algorithm = algorithm
        self.engine_opts = engine_opts

    def check(self, test, history: History, opts=None) -> Dict[str, Any]:
        from jepsen_tpu.checker.linearizable import Linearizable
        from jepsen_tpu.models import get_model
        kw = dict(self.model_kw)
        if self.derive is not None:
            derived = self.derive(history, kw)
            for k, v in derived.items():
                kw.setdefault(k, v)
        model = get_model(self.model_name, **kw)
        res = Linearizable(model, self.algorithm,
                           **self.engine_opts).check(test, history, opts)
        res.setdefault("model", model.name)
        return res


def derive_queue_slots(history: History,
                       kw: Dict[str, Any]) -> Dict[str, Any]:
    """Ring capacity for the fifo-queue device tier: at least the number
    of enqueue invocations (a linearization can never hold more), rounded
    onto the pow2 ladder (floor 8) so queue checks of similar size share
    one compiled engine shape."""
    if "slots" in kw:
        return {}
    from jepsen_tpu.engine.ladder import pow2_at_least
    n_enq = sum(1 for op in history
                if op.invoke_ and op.f == "enqueue")
    n_enq = max(n_enq, sum(1 for op in history
                           if not op.invoke_ and op.f == "enqueue"))
    return {"slots": pow2_at_least(n_enq, 8)}
