"""Witness-recovery discipline: device lanes flag, the CPU recovers.

A device lane refutes by emptying its configuration frontier — it knows
*which* op killed the last configuration but not the path that led
there.  The discipline, shared by every device engine: the device
result carries the refuting op (the lanes *flag*), and the knossos-style
final-configs witness is re-derived on the host by re-running the CPU
oracle on the failing prefix (cheap: the prefix is exactly what the
device already refuted).  A witness search exceeding its budget degrades
the *witness* to an error note — the refutation verdict itself stands,
because it was earned by exhaustive search, and conversely no code path
may fabricate a ``valid: False`` without a refuting op attached
(the SOUND01 contract).
"""

from __future__ import annotations

from typing import Any, Dict

#: Configuration budget for the CPU witness re-derivation on refuted
#: histories (knossos-style final-paths cost cap; checker.clj:213-216
#: truncates for the same reason).  Exceeding it degrades the result to
#: ``witness: {"error": ...}`` — the refutation verdict itself stands.
WITNESS_BUDGET = 200_000


def cpu_witness(model, history, failed_op,
                budget: int = WITNESS_BUDGET) -> Dict[str, Any]:
    """Re-run the CPU oracle on the prefix ending at the failing op's
    completion for a knossos-style final-configs report."""
    from jepsen_tpu.checker import wgl_cpu
    from jepsen_tpu.history import History
    h = history.client_ops().complete()
    pairs = h.pair_index()
    cut = None
    for i, op in enumerate(h):
        if op.index == failed_op.index:
            cut = int(pairs[i]) if pairs[i] >= 0 else i
            break
    if cut is None:
        return {"error": "failing op not found in history"}
    prefix = History(h.ops[:cut + 1])
    try:
        return wgl_cpu.check(model.cpu_model(), prefix, max_configs=budget)
    except wgl_cpu.SearchExploded:
        return {"error": "witness search exceeded budget"}


def refuted_result(analyzer: str, op, configs_explored: int,
                   **extra: Any) -> Dict[str, Any]:
    """The canonical device-lane refutation: the frontier emptied at
    ``op`` and the refuting op rides the verdict as its evidence."""
    # witness: exhaustive device search emptied the frontier; refuting op attached
    return {"valid": False, "analyzer": analyzer, "op": op.to_dict(),
            "configs-explored": int(configs_explored), **extra}
