"""The engine shape ladder: every compiled shape derives from pow2 buckets.

The derivations themselves live in :mod:`jepsen_tpu.serve.buckets` (the
ladder is a serving-policy decision measured there); this module owns
the *engine-side* half — turning a set of prepared histories plus a
bucket floor into the one shared engine shape a dispatch compiles for —
so the batch driver, the scheduler, and the trace-tier lint all read the
same derivation instead of three private copies.

Discipline (enforced by SHAPE01 at call sites and TRACE02 end-to-end):
every component of an engine cache key (window, capacity, chunk, lane
pad, gwords) must be a pure function of the bucket, never of a raw
history shape — one raw ``len(h)`` leaking in reopens the unbounded
compile cache the ladder exists to close.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

# Re-exported bucket derivations: engine consumers import the ladder from
# here; serve/buckets.py stays the single place the rungs are defined.
# The re-export is lazy (PEP 562): importing jepsen_tpu.serve.buckets
# executes serve/__init__, whose service/scheduler chain imports
# parallel.batch — which imports THIS module.  Resolving the names on
# first attribute access instead of at import time keeps the engine ->
# serve edge out of the import graph.
_BUCKET_EXPORTS = (
    "MAX_EPOCH_EVENTS_BUCKET", "MAX_LANE_BUCKET",
    "MIN_EPOCH_EVENTS_BUCKET", "MIN_EVENTS_BUCKET", "MIN_N_BUCKET",
    "MIN_STATE_WIDTH_BUCKET", "MIN_WIDTH_BUCKET", "elle_bucket",
    "elle_n_bucket", "epoch_events_bucket", "events_bucket", "lane_bucket",
    "mega_lane_bucket", "pow2_at_least", "state_width_bucket", "wgl_bucket",
    "wgl_start_capacity", "width_bucket",
)


def __getattr__(name: str):
    if name in _BUCKET_EXPORTS:
        from jepsen_tpu.serve import buckets
        return getattr(buckets, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

#: Target lane-events per dispatch: the vmapped scan costs ~(batch x
#: chunk) lane-event steps, so the chunk shrinks as the batch grows to
#: keep one XLA program's duration roughly constant regardless of batch
#: size.
LANE_EVENTS_PER_DISPATCH = 16384


def round_window(w: int) -> int:
    """Tightest engine window for a history: multiple of 4, >= 8."""
    return max(8, ((w + 3) // 4) * 4)


def batch_chunk(bpad: int, longest: int) -> int:
    """Events per dispatch for a ``bpad``-lane batch (multiple of 64,
    clamped to [64, 2048] and to the longest lane rounded up)."""
    c = max(64, min(2048, (LANE_EVENTS_PER_DISPATCH // max(1, bpad))
                    // 64 * 64))
    return min(c, max(64, ((longest + 63) // 64) * 64))


def batch_shape(preps: Sequence, window_floor: int = 0) -> Tuple[int, int, int]:
    """The one shared wgl engine shape for a batch of prepared histories:
    ``(window, gwords, longest)``.

    All lanes share one engine shape — window = max over histories
    (rounded onto the window ladder, floored by the caller's bucket),
    ghost words = max over lanes (lean gwords=0 only when EVERY lane
    qualifies: the shape is shared, and a non-qualifying lane's
    ghost_words dominates the max anyway)."""
    from jepsen_tpu.checker.wgl_tpu import chosen_gwords
    window = round_window(max(window_floor, max(p.window for p in preps)))
    gwords = max(chosen_gwords(p) for p in preps)
    longest = max(len(p) for p in preps)
    return window, gwords, longest


def pad_words(n: int, word: int = 32) -> int:
    """Round ``n`` up to a whole number of ``word``-sized words.  The one
    word-padding derivation in the stack: the elle adjacency pad
    (``elle_tpu``'s 32-row closure tiles) and any packed-bitmask state
    sizing round here instead of keeping private ``(n + 31) // 32 * 32``
    copies."""
    return ((max(0, n) + word - 1) // word) * word


def _state_halvings(state_width: int) -> int:
    """Rungs the state-width bucket sits above the register floor — the
    damping exponent shared by :func:`mega_chunk` and
    :func:`state_capacity`."""
    from jepsen_tpu.serve import buckets
    sw_bucket = buckets.state_width_bucket(state_width)
    return max(0, sw_bucket.bit_length()
               - buckets.MIN_STATE_WIDTH_BUCKET.bit_length())


def mega_chunk(bpad: int, longest: int, state_width: int) -> int:
    """Events per dispatch for a megabatch lane group, state-width
    aware: start from :func:`batch_chunk` and halve once per rung the
    model's packed state sits above the register floor (a queue ring or
    txn key vector multiplies the per-step merge cost by its width, so
    wide-state dispatches shorten to keep one XLA program's duration
    roughly constant).  Still a multiple of 64 with floor 64, and still
    a pure function of (lane bucket, events bucket, state-width bucket)
    — the raw ``state_width`` is quantized internally, so equal buckets
    always derive equal chunks."""
    c = batch_chunk(bpad, longest)
    c = (c >> _state_halvings(state_width)) // 64 * 64
    return max(64, c)


def state_capacity(ev_bucket: int, w_bucket: int, state_width: int) -> int:
    """The wgl *starting* capacity for a model with a ``state_width``-wide
    packed state: :func:`~jepsen_tpu.serve.buckets.wgl_start_capacity`
    shifted down one rung per state-width doubling past the register
    floor.  Wide states make each resident configuration proportionally
    more expensive (memory and merge cost both scale with the packed
    width), and under-starting is safe — overflow lanes escalate up the
    :func:`next_capacity` ladder — so the derivation trades a possible
    escalation round-trip for not compiling huge frontiers nobody needs.
    Pure function of the (ev, w, state-width) bucket triple; floored at
    ``MIN_WGL_CAPACITY``."""
    from jepsen_tpu.serve import buckets
    cap = buckets.wgl_start_capacity(ev_bucket, w_bucket)
    return max(buckets.MIN_WGL_CAPACITY,
               cap >> _state_halvings(state_width))


def next_capacity(cap: int, max_capacity: int, growth: int = 8) -> Optional[int]:
    """The next rung of the capacity-escalation ladder, or None when
    ``cap`` already hit the ceiling (the caller degrades the remaining
    lanes to ``unknown`` — never to false)."""
    if cap >= max_capacity:
        return None
    return min(cap * growth, max_capacity)
