"""Pulse: the device-resident streaming WGL tier.

The host monitor (:mod:`jepsen_tpu.monitor.epochs`) keeps one
:class:`~jepsen_tpu.monitor.epochs.KeyFrontier` per key and steps its
configuration search in Python.  This module keeps the same frontier
*on the device*: the config-set carry of the compiled WGL engine
(:func:`jepsen_tpu.checker.wgl_tpu.make_engine`) persists between
monitor epochs — donated in place, never re-uploaded — and each epoch
dispatches ONLY the ops that arrived since the last one, padded onto
the epoch-events rung of the shape ladder
(:func:`jepsen_tpu.serve.buckets.epoch_events_bucket`).  Per-epoch cost
is therefore bounded by new-ops work, flat in total history length.

Division of labour:

- :class:`_EventCursor` — the host :class:`KeyFrontier` with its closure
  unplugged: the inherited horizon loop does all the stream-order
  resolution (fail pairs removed, crashed ops ghosted, unconstraining
  crashed reads dropped, LIFO slot reuse — exactly ``checker.prep``'s
  event stream by construction), but ENTER/RETURN *emit device event
  rows* instead of stepping configurations.
- :class:`DeviceKeyFrontier` — owns the resident carry and the
  escalation ladder.  Soundness contract, in order of degradation:
  a device ``failed`` flag is never trusted directly — the raw prefix is
  replayed through a fresh host :class:`KeyFrontier` and ITS refutation
  dict is adopted verbatim (byte-identical to the host tier; a
  refutation on a prefix is final, so confirming on the same prefix is
  sound).  Capacity overflow climbs the ``next_capacity`` ladder
  (replaying the full event stream into a fresh carry — donation means
  no snapshots); at the ceiling, and on any device error or monitor-lane
  timeout, the frontier falls back STICKY to the host tier: unknown or
  host-verdict, never a fabricated false.
- :class:`StreamWglEpochEngine` — the per-key router, differing from
  :class:`WglEpochEngine` only in its frontier factory.

The engine is built LEAN (``gwords=0``): ghost subsumption is an
optimization, not a soundness condition, and the streaming cursor cannot
assign compact ghost positions online (prepare() numbers classes after
seeing the whole history).  Ghost-heavy streams simply explore more
configs, overflow earlier, and escalate — the ladder absorbs it.

Every compiled epoch executable is keyed ``("streamv", model, window,
capacity, epoch-bucket, ...)`` in the shared bounded engine cache, so N
concurrent monitored streams on the same rungs share ONE executable and
the steady state recompiles nothing.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import numpy as np

from jepsen_tpu.checker.wgl_tpu import (
    CLOSURE_WORK_BUDGET, EV_ENTER, EV_NOP, EV_RETURN, make_engine,
)
from jepsen_tpu.engine.cache import CACHE as _ENGINE_CACHE
from jepsen_tpu.engine.ladder import next_capacity, round_window
from jepsen_tpu.monitor.epochs import KeyFrontier, WglEpochEngine
from jepsen_tpu.obs.hist import timed_first_call
from jepsen_tpu.ops import dedup as _dedup
from jepsen_tpu.parallel.batch import donate_carry_argnums

#: capacity-escalation factor (same rung spacing as the batch tier)
CAPACITY_GROWTH = 8


def stream_engine_rungs(width: int, n_new: int):
    """The (window, start-capacity, epoch-chunk) rung triple for a
    stream whose pending window high-water is ``width`` with ``n_new``
    undispatched event rows.  Pure function of the (width bucket,
    epoch-events bucket) pair — the raw inputs are quantized here, so
    equal buckets always compile equal shapes (the TRACE02 stream leg
    asserts exactly this)."""
    from jepsen_tpu.serve import buckets
    wb = buckets.pow2_at_least(max(1, width), buckets.MIN_WIDTH_BUCKET)
    return (round_window(wb),
            buckets.wgl_start_capacity(buckets.MIN_EVENTS_BUCKET, wb),
            buckets.epoch_events_bucket(n_new))


def monitor_dispatcher(service):
    """The service's monitor-lane dispatch callable (device work rides
    the scheduler's device-loop thread, serialized with serve traffic),
    or None when no scheduler is attached — the frontier then runs its
    dispatches inline."""
    sched = getattr(service, "_sched", None)
    if sched is None or not hasattr(sched, "monitor_call"):
        return None
    return sched.monitor_call


class _EventCursor(KeyFrontier):
    """KeyFrontier's stream-order event loop with the configuration
    search unplugged: ENTER/RETURN emit ``checker.prep``-format device
    event rows ([kind, slot, f, a, b, op_id, ghost, gcls, grank, gpos])
    into ``self.rows``.  Ghost class columns are emitted inert
    (gcls=-1): the stream engine is always LEAN, where they are unused.
    Never refutes, never explodes — the device owns the verdict."""

    def __init__(self, model, jax_model, max_configs: int = 2_000_000):
        super().__init__(model, max_configs=max_configs)
        self.jax_model = jax_model
        self.rows: List[List[int]] = []
        self._slot_opid: Dict[int, int] = {}
        self.op_seq = 0

    def _enter(self, eff, ghost, comp) -> None:
        s = self._alloc_slot()
        self.window[s] = eff
        self.ops_entered += 1
        f, a, b = self.jax_model.encode_op(eff)
        op_id = self.op_seq
        self.op_seq += 1
        self._slot_opid[s] = op_id
        self.rows.append([EV_ENTER, s, int(f), int(a), int(b), op_id,
                          1 if ghost else 0, -1, 0, 0])
        if ghost:
            self.ghost_mask |= 1 << s
            self.n_ghosts += 1
        elif comp is not None:
            self._return_slot[comp.index] = s

    def _return(self, slot, comp) -> None:
        op_id = self._slot_opid.pop(slot, 0)
        self.rows.append([EV_RETURN, slot, 0, 0, 0, op_id, 0, -1, 0, 0])
        del self.window[slot]
        self._free.append(slot)
        self.ops_checked += 1


class DeviceKeyFrontier:
    """One key's WGL frontier, resident on the device between epochs.

    Same surface as :class:`KeyFrontier` (feed / advance / finalize /
    pending_ops / verdict, plus the counters the epoch engine sums), so
    the monitor, the verdict channel, and resume.py cannot tell the
    tiers apart.  ``self.prefix`` always retains the raw fed ops: it is
    the replay source for escalation, refutation confirmation, and the
    sticky host fallback."""

    def __init__(self, jax_model, model, max_configs: int = 2_000_000,
                 capacity: Optional[int] = None,
                 max_capacity: Optional[int] = None, dispatcher=None):
        from jepsen_tpu.serve import buckets
        self.jax_model = jax_model
        self.model = model
        self.max_configs = max_configs
        self.capacity_opt = capacity
        self.max_capacity = (buckets.MAX_WGL_CAPACITY
                             if max_capacity is None else max_capacity)
        self.prefix: List[Any] = []
        self.result: Optional[Dict[str, Any]] = None
        self.exploded: Optional[str] = None
        self.fallback_reason: Optional[str] = None
        self.epoch_dispatches = 0
        self.escalations = 0
        self._cursor = _EventCursor(model, jax_model,
                                    max_configs=max_configs)
        self._dispatcher = dispatcher
        self._host: Optional[KeyFrontier] = None   # sticky fallback
        self._carry = None
        self._applied = 0                          # rows in the carry
        self._explored = 0
        self._finalizing = False
        window, start_cap, _ = stream_engine_rungs(1, 1)
        self._window = window
        self._capacity = capacity or start_cap

    # -- ingest / epoch surface -------------------------------------------
    def feed(self, op) -> None:
        self.prefix.append(op)
        (self._host if self._host is not None else self._cursor).feed(op)

    def advance(self) -> Optional[Dict[str, Any]]:
        if self.result is not None or self.exploded is not None:
            self._cursor._stream.clear()
            return None
        if self._host is not None:
            r = self._host.advance()
            self.result = self._host.result
            self.exploded = self._host.exploded
            return r
        before = self.result
        self._cursor.advance()      # emits rows; cannot refute or explode
        self._advance_device()
        return self.result if self.result is not before else None

    def finalize(self) -> None:
        self._finalizing = True
        if self._host is not None:
            self._host.finalize()
            self.result = self._host.result
            self.exploded = self._host.exploded
            return
        if self.result is not None or self.exploded is not None:
            return
        self._cursor.finalize()
        self._advance_device()

    def pending_ops(self) -> int:
        return (self._host if self._host is not None
                else self._cursor).pending_ops()

    @property
    def ops_entered(self) -> int:
        return (self._host if self._host is not None
                else self._cursor).ops_entered

    @property
    def ops_checked(self) -> int:
        return (self._host if self._host is not None
                else self._cursor).ops_checked

    @property
    def n_explored(self) -> int:
        if self._host is not None:
            return self._host.n_explored
        return self._explored

    def verdict(self) -> Dict[str, Any]:
        if self._host is not None:
            return self._host.verdict()     # byte-identical host tier
        if self.result is not None:
            return dict(self.result)        # adopted host refutation
        if self.exploded is not None:
            return {"valid": "unknown", "analyzer": "wgl-stream",
                    "error": self.exploded,
                    "configs-explored": self._explored}
        live = (int(np.asarray(self._carry[2]).sum())
                if self._carry is not None else 1)
        return {"valid": True, "analyzer": "wgl-stream",
                "configs-explored": self._explored,
                "final-configs-count": live,
                "window": self._window, "capacity": self._capacity}

    # -- device driver ----------------------------------------------------
    def _engine(self, ep_bucket: int):
        m = self.jax_model
        key = ("streamv", m.name, m.variant, m.state_size,
               tuple(m.init_state_array().tolist()), self._window,
               self._capacity, ep_bucket, _dedup.N_PROBES,
               _dedup.WIDE_SORT_ROWS, _dedup.SUBSUME, CLOSURE_WORK_BUDGET)
        hit = _ENGINE_CACHE.get(key)
        if hit is not None:
            return hit
        carry0, _, run_chunk = make_engine(m, self._window, self._capacity,
                                           gwords=0)
        # Donated carry: the frontier's config set updates in place and
        # stays resident across epochs.  Donation forbids snapshots, so
        # every escalation replays the full event stream instead of
        # resuming — rungs only grow, so each is paid at most once.
        run = timed_first_call(
            jax.jit(run_chunk, donate_argnums=donate_carry_argnums()),
            f"compile:streamv:{m.name}:w{self._window}"
            f":c{self._capacity}:e{ep_bucket}")
        return _ENGINE_CACHE.put(key, (carry0, run))

    def _grow_window(self, width: int) -> None:
        window, start_cap, _ = stream_engine_rungs(width, 1)
        self._window = window
        self._capacity = max(self._capacity,
                             self.capacity_opt or start_cap)
        self._carry = None
        self._applied = 0
        self.escalations += 1

    def _advance_device(self) -> None:
        import jax.numpy as jnp
        from jepsen_tpu.serve import buckets
        cur = self._cursor
        if cur._next_slot > self._window:
            self._grow_window(cur._next_slot)
        rows = cur.rows
        while (self.result is None and self.exploded is None
               and self._host is None and self._applied < len(rows)):
            remaining = len(rows) - self._applied
            b = buckets.epoch_events_bucket(remaining)
            take = min(remaining, b)
            chunk = np.zeros((b, 10), np.int32)
            chunk[:, 0] = EV_NOP
            chunk[:take] = np.asarray(
                rows[self._applied:self._applied + take], np.int32)
            carry0, run = self._engine(b)
            carry_in = self._carry if self._carry is not None else carry0()

            def dispatch(carry_in=carry_in, run=run, chunk=chunk):
                carry, flags = run(carry_in, jnp.asarray(chunk))
                return carry, np.asarray(flags)

            try:
                if self._dispatcher is not None:
                    carry, fl = self._dispatcher(dispatch)
                else:
                    carry, fl = dispatch()
            except Exception as e:  # noqa: BLE001 — timeout, stopped
                # loop, or device error: the carry's state is no longer
                # trustworthy (a timed-out dispatch may still land on
                # it later), so the device path is abandoned for good.
                self._fall_back(f"stream dispatch failed: {e}")
                return
            self._carry = carry
            self.epoch_dispatches += 1
            failed, overflow = bool(fl[0]), bool(fl[1])
            consumed = int(fl[3])
            if overflow:
                # Overflow may have dropped configurations, which could
                # fake an empty-survivor refutation — escalate FIRST and
                # never read the failed flag off an overflowed chunk.
                nxt = next_capacity(self._capacity, self.max_capacity,
                                    growth=CAPACITY_GROWTH)
                if nxt is None:
                    self._fall_back("configuration capacity exceeded at "
                                    f"{self._capacity}")
                    return
                self._capacity = nxt
                self._carry = None
                self._applied = 0
                self.escalations += 1
                continue
            self._applied += min(consumed, take)
            if failed:
                self._confirm_refutation()
                return
            # consumed < take is a closure-budget pause: loop around and
            # redispatch the remainder with a fresh budget.
        if self._carry is not None and self._host is None:
            self._explored = int(np.asarray(self._carry[9]))

    # -- degradation ladder ----------------------------------------------
    def _host_replay(self) -> KeyFrontier:
        f = KeyFrontier(self.model, max_configs=self.max_configs)
        for op in self.prefix:
            f.feed(op)
        if self._finalizing:
            f.finalize()
        else:
            f.advance()
        return f

    def _confirm_refutation(self) -> None:
        """The device flagged a refutation: replay the raw prefix through
        the host tier and adopt ITS result dict verbatim — refutations
        stay byte-identical to the host monitor's.  A disagreeing replay
        (host says valid or explodes) degrades to unknown, never to a
        device-only false."""
        f = self._host_replay()
        if f.result is not None:
            self.result = f.result
        elif f.exploded is not None:
            self.exploded = f.exploded
        else:
            self.exploded = ("device refutation unconfirmed by host "
                             "replay")

    def _fall_back(self, reason: str) -> None:
        """Sticky host fallback: replay the prefix into a fresh host
        frontier and route every later feed/advance through it.  The
        device carry is dropped and never consulted again."""
        self.fallback_reason = reason
        self._carry = None
        f = self._host_replay()
        self._host = f
        self.result = f.result
        self.exploded = f.exploded


class StreamWglEpochEngine(WglEpochEngine):
    """WglEpochEngine whose frontiers live on the device.  ``model`` may
    be a registry name (resolves both tiers) or a host model paired with
    an explicit ``jax_model``; without a device model the factory simply
    hands out host frontiers — the knob degrades, it never breaks."""

    def __init__(self, model, jax_model=None, independent: bool = False,
                 max_configs: int = 2_000_000, keep_prefix: bool = False,
                 service=None, capacity: Optional[int] = None,
                 max_capacity: Optional[int] = None):
        if jax_model is None and isinstance(model, str):
            from jepsen_tpu.models import get_model
            jax_model = get_model(model)
        if isinstance(model, str) and jax_model is not None:
            model = jax_model.cpu_model()   # host tier for replays
        super().__init__(model, independent=independent,
                         max_configs=max_configs, keep_prefix=keep_prefix)
        self.jax_model = jax_model
        self.service = service
        self.capacity = capacity
        self.max_capacity = max_capacity

    def _new_frontier(self):
        if self.jax_model is None:
            return super()._new_frontier()
        return DeviceKeyFrontier(self.jax_model, self.model,
                                 max_configs=self.max_configs,
                                 capacity=self.capacity,
                                 max_capacity=self.max_capacity,
                                 dispatcher=monitor_dispatcher(self.service))

    def counters(self) -> Dict[str, int]:
        c = super().counters()
        c["epoch-dispatches"] = sum(
            getattr(f, "epoch_dispatches", 0)
            for f in self.frontiers.values())
        c["fallbacks"] = sum(
            1 for f in self.frontiers.values()
            if getattr(f, "fallback_reason", None) is not None)
        return c
