"""Opacity checking via the opacity -> linearizability reduction.

Opacity (Guerraoui & Kapalka) demands that ALL transactions — committed
*and* aborted — observe one consistent serial order.  The reduction
(arXiv:1610.01004, "Checking Opacity of Transactional Memories"): a
transactional history is opaque iff the derived history in which

- a **committed** transaction is one atomic op applying its reads and
  writes (``f="txn"``),
- an **aborted** transaction is one atomic *read-only* op — its writes
  are discarded (they never took effect) but its reads must still have
  seen a consistent snapshot (``f="txn-ro"``); an aborted txn that
  observed nothing constrains nothing and is dropped,
- a **crashed** transaction (info/no completion) stays an open op whose
  writes may or may not have applied — the engine's standard ghost
  discipline,

is linearizable over the sequential transactional-register oracle.  The
derived history runs on the UNCHANGED wgl engine (device tier:
``models.collections.txn_register_jax``, a plain int32 state machine) —
opacity rides the substrate as a drop-in model plugin, no engine change.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from jepsen_tpu.checker.core import Checker
from jepsen_tpu.history import FAIL, History, OK

#: arXiv reference for the reduction this checker implements.
REDUCTION = "opacity->linearizability (arXiv:1610.01004)"


def derive_history(history: History) -> History:
    """The reduction's history transform (see module docstring).

    Aborted ``txn`` pairs are retyped to ok ``txn-ro`` pairs carrying
    only their constraining reads (observed, non-nil values); aborted
    txns with no such reads are dropped entirely.  Committed and crashed
    txns, and non-txn ops (nemesis lines), pass through untouched.
    """
    h = history if isinstance(history, History) else History(history)
    pairs = h.pair_index()
    drop = set()
    replace: Dict[int, Any] = {}
    for i, op in enumerate(h):
        if op.type != FAIL or op.f != "txn":
            continue
        j = int(pairs[i])
        mops = op.value
        if mops is None and j >= 0:
            mops = h.ops[j].value
        # Constraining reads only: observed (non-nil) values of keys NOT
        # written earlier in the same txn — a read-own-write observation
        # is satisfied internally and says nothing about global state
        # (the discarded write it saw never happened).
        written = set()
        reads = []
        for m in (mops or ()):
            if m[0] in ("w", "write"):
                written.add(m[1])
            elif m[0] in ("r", "read") and m[2] is not None \
                    and m[1] not in written:
                reads.append(list(m))
        if not reads:
            drop.add(i)
            if j >= 0:
                drop.add(j)
            continue
        replace[i] = op.with_(type=OK, f="txn-ro", value=reads,
                              error=None)
        if j >= 0:
            replace[j] = h.ops[j].with_(f="txn-ro", value=reads)
    ops = [replace.get(i, op) for i, op in enumerate(h.ops)
           if i not in drop]
    return History(ops, reindex=True)


class OpacityChecker(Checker):
    """Drop-in checker: opacity of a transactional history, decided by
    the unchanged wgl engine on the derived history.

    ``keys``/``vbits`` bound the device tier's register domain (the
    facade falls back to the host oracle outside it); ``algorithm`` and
    ``engine_opts`` pass straight through to :class:`Linearizable`.
    """

    def __init__(self, keys: int = 3, vbits: int = 4,
                 algorithm: Optional[str] = None, **engine_opts):
        self.keys = keys
        self.vbits = vbits
        self.algorithm = algorithm
        self.engine_opts = engine_opts

    def check(self, test, history: History, opts=None) -> Dict[str, Any]:
        from jepsen_tpu.checker.linearizable import Linearizable
        from jepsen_tpu.models import get_model
        derived = derive_history(history)
        model = get_model("txn-register", keys=self.keys, vbits=self.vbits)
        res = Linearizable(model, self.algorithm,
                           **self.engine_opts).check(test, derived, opts)
        res["checker"] = "opacity"
        res["reduction"] = REDUCTION
        res["derived-ops"] = len(derived)
        return res
