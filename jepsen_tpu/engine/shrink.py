"""Window-shrinking recursion: refute a giant un-splittable search.

Fission (engine.fission) handles an overflowing frontier by splitting it
— per-key components (arXiv 1504.00204) or ghost case-splits
(arXiv 2410.04581).  When neither splitter applies (one giant connected
component, too many ghosts to enumerate), the pre-fission behavior is a
monolithic escalation to the caller's real ceiling — a capacity a fleet
worker may simply not have.  This module is the third fallback, the
decrease-and-conquer recursion of arXiv 2410.04581 applied to the
*event window* instead of the crash set: recursively narrow the checked
prefix of the history until the frontier fits the threshold, hunting
for a refutation the full-width search could not reach.

Soundness (why a prefix refutation is a real refutation): take the
prefix of the first ``m`` op records.  Ops invoked before the cut but
completed after it lose their completions and are treated as
concurrent-to-the-end (exactly the crashed-op semantics every checker
in this repo already implements).  If the full history were
linearizable, ordering its witness by linearization points and
truncating at the cut yields a legal sequential prefix containing every
op completed before the cut, no op invoked after it, and some subset of
the cut-spanning ops — which is precisely a crash-semantics
linearization of the prefix.  Contrapositive: a refuted prefix refutes
the whole history.  The converse direction does NOT hold — a passing
prefix proves nothing about the suffix — so the shrink verdict envelope
is **False (with the prefix's witness) or unknown, never True**: the
recursion widens the window after a pass, narrows it after an overflow,
and gives up (``unknown``) when the interval closes without a
refutation.  Unknown-never-false holds on every path.

Knobs (README env table): ``JTPU_SHRINK`` (default on — engaged only
after escalation has already failed), ``JTPU_SHRINK_DEPTH`` (default 6
prefix probes), ``JTPU_SHRINK_MIN_EVENTS`` (default 64 — the narrowest
window worth checking).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

from jepsen_tpu.history import History
from jepsen_tpu.models.base import JaxModel
from jepsen_tpu.obs.hist import HistogramSet
from jepsen_tpu.obs.recorder import RECORDER

ANALYZER = "wgl-tpu-shrink"

DEFAULT_DEPTH = 6
DEFAULT_MIN_EVENTS = 64

#: Per-probe wall-clock histogram, merged into the /metrics fission
#: section beside engine.fission's.
HISTS = HistogramSet()


def shrink_enabled() -> bool:
    return os.environ.get("JTPU_SHRINK", "1").lower() \
        not in ("0", "false", "no", "off", "")


def shrink_depth() -> int:
    try:
        return max(1, int(os.environ.get("JTPU_SHRINK_DEPTH",
                                         DEFAULT_DEPTH)))
    except ValueError:
        return DEFAULT_DEPTH


def shrink_min_events() -> int:
    try:
        return max(2, int(os.environ.get("JTPU_SHRINK_MIN_EVENTS",
                                         DEFAULT_MIN_EVENTS)))
    except ValueError:
        return DEFAULT_MIN_EVENTS


# ---------------------------------------------------------------------------
# Counters (fission_stats idiom; exported in the /metrics fission section)
# ---------------------------------------------------------------------------

_STATS_LOCK = threading.Lock()


def _zero_stats() -> Dict[str, int]:
    return {"shrink_checks": 0, "shrink_probes": 0,
            "shrink_refutes": 0, "shrink_exhausted": 0}


_STATS = _zero_stats()


def shrink_stats() -> Dict[str, int]:
    """Counters over every shrink recursion in this process: recursions
    entered, prefix probes run, refutations found, and recursions that
    closed their interval without concluding."""
    with _STATS_LOCK:
        return dict(_STATS)


def reset_shrink_stats() -> None:
    with _STATS_LOCK:
        _STATS.update(_zero_stats())


def _bump(**kw: int) -> None:
    with _STATS_LOCK:
        for k, v in kw.items():
            _STATS[k] += v


# ---------------------------------------------------------------------------
# The recursion
# ---------------------------------------------------------------------------

def prefix_history(history: History, m: int) -> History:
    """The first ``m`` op records as a standalone history.  Invokes whose
    completions fall past the cut stay open — prepare() treats them as
    crashed (concurrent-to-the-end), which is exactly the weakening the
    soundness argument in the module docstring needs."""
    return History(list(history.ops[:m]), reindex=True)


def shrink_check(model: JaxModel, history: History, *,
                 threshold: int,
                 capacity: int = 256,
                 max_depth: Optional[int] = None,
                 min_events: Optional[int] = None,
                 explain: bool = True, **opts: Any) -> Dict[str, Any]:
    """Hunt for a refutation of ``history`` inside prefixes whose
    frontiers fit ``threshold``.

    Returns a refuted result (op + witness, both derived on the refuting
    prefix only) or ``unknown`` — never True: a prefix pass widens the
    probe window instead of concluding.  ``capacity`` seeds each probe's
    ladder; remaining kwargs pass through to ``wgl_tpu.check``."""
    from jepsen_tpu.checker import wgl_tpu
    depth = max_depth if max_depth is not None else shrink_depth()
    floor = min_events if min_events is not None else shrink_min_events()
    h = history.client_ops()
    n = len(h.ops)
    _bump(shrink_checks=1)
    t0 = time.monotonic()
    lo, hi = min(floor, n), n          # (lo..hi]: the probe interval
    m = max(lo, n // 2)
    probes = 0
    windows = []
    while probes < depth and lo < hi:
        probes += 1
        p = prefix_history(h, m)
        tp = time.monotonic()
        r = wgl_tpu.check(model, p, capacity=min(capacity, threshold),
                          max_capacity=threshold, explain=explain, **opts)
        HISTS.observe("fission:shrink-probe", time.monotonic() - tp)
        windows.append({"events": m, "valid": r.get("valid"),
                        "configs-explored": r.get("configs-explored", 0)})
        if r.get("valid") is False:
            _bump(shrink_probes=probes, shrink_refutes=1)
            RECORDER.record("fission", "shrink-refute",
                            dur_s=time.monotonic() - t0,
                            args={"events": m, "probes": probes})
            # The prefix's refutation IS the history's (prefix closure
            # under crash semantics — module docstring); op and witness
            # come from the refuting prefix only.
            # witness: refuting prefix's op + witness attached verbatim; prefix refutation is sound for the whole history
            out = {"valid": False, "analyzer": ANALYZER,
                   "op": r.get("op"),
                   "configs-explored": int(r.get("configs-explored", 0)
                                           or 0),
                   "fission": {"mode": "shrink", "events": m,
                               "probes": probes, "windows": windows}}
            if "witness" in r:
                out["witness"] = r["witness"]
            return out
        if r.get("capacity-exceeded") \
                or "capacity exceeded" in str(r.get("error", "")):
            hi = m                     # too wide: narrow the window
            m = max(lo, (lo + m) // 2)
            if m >= hi:
                break
        elif r.get("valid") is True:
            lo = m                     # passes: the refutation (if any)
            m = (m + hi + 1) // 2      # needs more events — widen
            if m > hi or m == lo:
                break
        else:
            break                      # a non-overflow unknown (budget,
            #                            deadline) — shrinking won't help
    _bump(shrink_probes=probes, shrink_exhausted=1)
    RECORDER.record("fission", "shrink-exhausted",
                    dur_s=time.monotonic() - t0,
                    args={"probes": probes, "lo": lo, "hi": hi})
    return {"valid": "unknown", "analyzer": ANALYZER,
            "error": f"shrink recursion exhausted after {probes} prefix "
                     f"probe(s) without a refutation",
            "configs-explored": sum(int(w.get("configs-explored", 0) or 0)
                                    for w in windows),
            "fission": {"mode": "shrink", "probes": probes,
                        "windows": windows}}
