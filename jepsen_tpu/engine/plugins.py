"""Device-model plugin seam: new consistency models as registry entries.

A "plugin" is a named checker wired from (a) a registered device model
(:func:`~jepsen_tpu.models.base.register_model`) and (b) the shared
engine substrate — ladder, cache, budget, fallback, witness — via the
:class:`~jepsen_tpu.engine.model_plugin.ModelPluginChecker` facade.
Writing a new consistency model means writing the int32 step/encode pair
and one ``register_model_plugin`` line; the engine itself is untouched
(see docs/engines.md for the walkthrough).

This module is import-light on purpose: ``checker.core`` imports it from
``_register_builtins()`` while core itself is still mid-import, so
nothing here may import checker modules (or jax) at module scope —
factories resolve lazily at checker-construction time.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

# name -> {"model": model-name or None, "doc": one-liner} for discovery
# (docs, the engine smoke, `registered_plugins()`).
_PLUGINS: Dict[str, Dict[str, Any]] = {}


def register_model_plugin(name: str, model: str, register: Callable,
                          doc: str = "",
                          derive: Optional[Callable] = None,
                          **preset: Any) -> None:
    """Register checker ``name`` as linearizability over device model
    ``model`` through the substrate facade.  ``register`` is the checker
    registry hook (checker.core.register_checker — passed in, not
    imported, to keep this module cycle-free); ``preset`` kwargs become
    factory defaults the spec's opts override."""
    def factory(**opts):
        from jepsen_tpu.engine.model_plugin import ModelPluginChecker
        merged = {**preset, **opts}
        model_kw = merged.pop("model_kw", None)
        return ModelPluginChecker(model, model_kw=model_kw,
                                  derive=derive, **merged)
    register(name, factory)
    _PLUGINS[name] = {"model": model, "doc": doc}


def registered_plugins() -> List[str]:
    """Names of the checkers registered through the plugin seam."""
    return sorted(_PLUGINS)


# Megabatch carry descriptors: model-family name -> {"doc", "derive"}.
# Registering says "this family's per-configuration state packs as the
# flat int32 vector JaxModel.carry_descriptor() describes, so its lanes
# may bin-pack into the megabatch donated-carry loop".  The scheduler's
# _mega_eligible consults this instead of hard-coding a family; a model
# without an entry is never rejected — it just keeps the check_batch
# barrier path.  ``derive`` names the family's history->sizing hook
# (e.g. derive_queue_slots) whose pow2 outputs feed the state-width
# bucket key.  Populated lazily for the same cycle-safety reason as the
# plugin registry above.
_CARRIES: Dict[str, Dict[str, Any]] = {}
_CARRIES_SEEDED = False


def register_carry_descriptor(model: str, doc: str = "",
                              derive: Optional[Callable] = None) -> None:
    """Opt device-model family ``model`` into megabatch routing."""
    _CARRIES[model] = {"doc": doc, "derive": derive}


def _seed_builtin_carries() -> None:
    global _CARRIES_SEEDED
    if _CARRIES_SEEDED:
        return
    _CARRIES_SEEDED = True
    from jepsen_tpu.engine.model_plugin import derive_queue_slots
    for name, doc in (
            ("register", "single int32 register cell"),
            ("cas-register", "register + CAS, same single-cell state"),
            ("mutex", "single lock-owner cell"),
            ("owner-aware-mutex", "lock-owner cell keyed by process"),
            ("reentrant-mutex", "owner + depth pair"),
            ("multi-register", "one cell per key, width = keys"),
            ("bitset", "packed mask words, width = ceil(domain/31)"),
            ("bitset-256", "fixed 9-word packed mask"),
            ("set", "two-word bitmask, domain [0, 62)"),
            ("txn-register", "one cell per key, width = keys"),
    ):
        register_carry_descriptor(name, doc=doc)
    register_carry_descriptor(
        "fifo-queue", doc="ring buffer, width = 2 + slots (pow2-derived)",
        derive=derive_queue_slots)


def has_carry_descriptor(model: str) -> bool:
    """True when model family ``model`` registered a megabatch carry
    descriptor (the routing gate ``scheduler._mega_eligible`` asks)."""
    _seed_builtin_carries()
    return model in _CARRIES


def carry_descriptors() -> List[str]:
    """Model families opted into megabatch routing."""
    _seed_builtin_carries()
    return sorted(_CARRIES)


def carry_info(model: str) -> Dict[str, Any]:
    _seed_builtin_carries()
    return dict(_CARRIES[model])


def plugin_info(name: str) -> Dict[str, Any]:
    return dict(_PLUGINS[name])


def register_builtin_plugins(register: Callable) -> None:
    """The builtin plugin battery (called by checker.core's
    ``_register_builtins``): the queue and set device kernels, and the
    opacity checker via the opacity->linearizability reduction."""
    from jepsen_tpu.engine.model_plugin import derive_queue_slots
    register_model_plugin(
        "linearizable-queue", "fifo-queue", register,
        doc="FIFO queue linearizability on the device engine "
            "(ring-buffer kernel; slots derived from the history, "
            "bucketed pow2)",
        derive=derive_queue_slots)
    register_model_plugin(
        "linearizable-set", "set", register,
        doc="read-full-set linearizability on the device engine "
            "(two-word bitmask kernel, domain [0, 62))")

    def opacity_factory(**opts):
        from jepsen_tpu.engine.opacity import OpacityChecker
        return OpacityChecker(**opts)
    register("opacity", opacity_factory)
    _PLUGINS["opacity"] = {
        "model": "txn-register",
        "doc": "opacity via the opacity->linearizability reduction "
               "(arXiv:1610.01004) on the unchanged wgl engine"}
