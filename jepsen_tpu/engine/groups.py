"""Lane grouping: every vmapped dispatch stays under the 512-lane cap.

Root cause (minimized to pure JAX, reproduces on CPU and TPU backends
and with eager vmap): a vmapped scatter into a BOOL array inside
``lax.scan`` computes wrong results at batch >= 1024 —
``jax.vmap(lambda arr, slot: arr.at[slot].set(False))`` over bool[W]
carriers, exactly the wgl engine's ``active``/``fresh`` slot updates;
int32 carriers are unaffected, 1023 lanes are verdict-perfect (see
tests/test_parallel.py regression and ops/jax_bug_repro.py).  512 is
also the throughput knee measured in the one-off hardware tuning sweep
(58.9 h/s at 512 lanes vs 52.1 at 256 on 200-op lanes), so grouping
costs nothing.

Both device engines group through here: wgl batches slice at the flat
cap, elle lowers the cap further so one dispatch's adjacency residency
stays bounded as histories grow.
"""

from __future__ import annotations

from typing import Iterator, Tuple

#: Max lanes per vmapped dispatch group (the bool-scatter cliff /
#: measured throughput knee — see module docstring).
MAX_LANES_PER_GROUP = 512


def group_slices(n_items: int,
                 cap: int = MAX_LANES_PER_GROUP) -> Iterator[Tuple[int, int, bool]]:
    """Bounded dispatch groups over ``n_items`` lanes: yields
    ``(start, stop, group_reuse)`` slices of at most ``cap`` lanes.
    ``group_reuse`` is False only for the first group — later groups of
    one logical batch count as cache ``group_reuses``, not ``hits`` (see
    :meth:`EngineCache.get`)."""
    cap = max(1, int(cap))
    for start in range(0, n_items, cap):
        yield start, min(start + cap, n_items), start > 0


def bounded_group_cap(cell_budget: int, cells_per_lane: int,
                      cap: int = MAX_LANES_PER_GROUP) -> int:
    """Lanes per group when each lane pins ``cells_per_lane`` device
    cells and one dispatch may hold at most ``cell_budget`` of them (the
    elle engine's adjacency-residency bound): the flat lane cap, lowered
    so lanes*cells stays under budget."""
    return max(1, min(cap, cell_budget // max(1, cells_per_lane)))
