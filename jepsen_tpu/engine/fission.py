"""Frontier fission: split the WGL search instead of escalating capacity.

The capacity-escalation ladder (engine.ladder) treats an overflowing
configuration frontier as a *sizing* problem: compile a bigger engine and
re-run.  Past a point that is the wrong physics — per-round sort cost
scales with the static capacity, the 65536 ceiling turns into a hard
``valid: unknown`` wall, and one giant frontier monopolizes the device
while the batch/megabatch lanes it could have become sit idle.  This
module turns the wall into *fission*: when escalation would cross a
configurable threshold, the search splits into sub-problems whose
frontiers fit small, cache-hot bucket shapes, and the sub-verdicts
recombine under the engine substrate's unknown-never-false discipline.

Two splitters, applied in order:

1. **Component split (P-compositionality, arXiv 1504.00204).**  When the
   model declares per-key independence (``JaxModel.components``), the
   history partitions into sub-histories over connected components of
   touched keys — the Herlihy–Wing locality theorem makes the conjunction
   exact: the history is linearizable iff every projection is, and a
   refuted projection refutes the whole.  This pushes ``serve/decompose``'s
   admission-time per-key projection into the search itself, where it also
   fires on histories that arrived as one cell.

2. **Ghost case-split (decrease-and-conquer, arXiv 2410.04581).**  With no
   independence to exploit, the frontier blowup is almost always the
   2^ghosts ambiguity of crashed ops (each may or may not have taken
   effect).  The split enumerates that ambiguity *outside* the engine: a
   history is linearizable iff for SOME subset S of its ghosts the variant
   "force S (must linearize by stream end), elide the rest (never took
   effect)" is linearizable — an exact disjunction.  Every variant is
   ghost-free, so it runs the lean engine on a small shape; the all-elided
   variant is checked first (a valid verdict short-circuits the whole
   disjunction), and the remaining 2^k - 1 variants dispatch as ordinary
   batch lanes (small ones through megabatch).

Recombination rules (the SOUND01 contract, table form in docs/fission.md):

===============  ==========================================================
sub-verdicts      combined verdict
===============  ==========================================================
components: any False   False — refuting op + witness from that sub-problem only
components: all True    True
components: else        unknown (never false)
ghosts: any True        True
ghosts: all False       False — witness from the all-elided sub-problem
ghosts: else            monolithic escalation to the caller's real ceiling
===============  ==========================================================

Knobs (README env table): ``JTPU_FISSION`` (default on),
``JTPU_FISSION_THRESHOLD`` (default 16384 — the last capacity rung reached
before splitting), ``JTPU_FISSION_MAX_SUBPROBLEMS`` (default 256 — caps
the ghost enumeration at 2^8 variants).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from jepsen_tpu.history import FAIL, History, INFO, INVOKE, OK, Op
from jepsen_tpu.models.base import JaxModel, UNKNOWN32
from jepsen_tpu.obs.hist import HistogramSet
from jepsen_tpu.obs.recorder import RECORDER

DEFAULT_THRESHOLD = 16384
DEFAULT_MAX_SUBPROBLEMS = 256

ANALYZER = "wgl-tpu-fission"

#: Sub-problem wall-clock histograms, exported with the fission counters
#: in the serve /metrics snapshot (PR 10 observability discipline).
HISTS = HistogramSet()


# ---------------------------------------------------------------------------
# Knobs
# ---------------------------------------------------------------------------

def fission_enabled() -> bool:
    return os.environ.get("JTPU_FISSION", "1").lower() \
        not in ("0", "false", "no", "off", "")


def fission_threshold() -> int:
    """Capacity rung past which the search splits instead of escalating."""
    try:
        return max(1, int(os.environ.get("JTPU_FISSION_THRESHOLD",
                                         DEFAULT_THRESHOLD)))
    except ValueError:
        return DEFAULT_THRESHOLD


def fission_max_subproblems() -> int:
    """Ceiling on ghost-enumeration variants (2^ghosts must fit)."""
    try:
        return max(2, int(os.environ.get("JTPU_FISSION_MAX_SUBPROBLEMS",
                                         DEFAULT_MAX_SUBPROBLEMS)))
    except ValueError:
        return DEFAULT_MAX_SUBPROBLEMS


# ---------------------------------------------------------------------------
# Counters (megabatch_stats idiom; exported in the /metrics snapshot)
# ---------------------------------------------------------------------------

_STATS_LOCK = threading.Lock()


def _zero_stats() -> Dict[str, int]:
    return {"checks": 0, "splits": 0,
            "component_splits": 0, "component_subproblems": 0,
            "ghost_splits": 0, "ghost_subproblems": 0,
            "recombines": 0, "short_circuits": 0,
            "sub_overflows": 0, "escalations": 0, "errors": 0}


_STATS = _zero_stats()


def fission_stats() -> Dict[str, int]:
    """Counters over every fission decision in this process: splits taken,
    sub-problems spawned per splitter, recombinations, all-elided
    short-circuits, sub-problems that themselves overflowed the threshold,
    and monolithic escalations (the pre-fission behavior, taken only when
    neither splitter can decide)."""
    with _STATS_LOCK:
        return dict(_STATS)


def reset_fission_stats() -> None:
    with _STATS_LOCK:
        _STATS.update(_zero_stats())


def _bump(**kw: int) -> None:
    with _STATS_LOCK:
        for k, v in kw.items():
            _STATS[k] += v


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

def check(model: JaxModel, history: Optional[History] = None,
          prepared: Any = None,
          capacity: int = 1024, max_capacity: int = 65536,
          threshold: Optional[int] = None,
          max_subproblems: Optional[int] = None,
          fission: Optional[bool] = None,
          explain: bool = True, **opts: Any) -> Dict[str, Any]:
    """Drop-in for :func:`jepsen_tpu.checker.wgl_tpu.check` with frontier
    fission above the threshold.

    Below the threshold this IS ``wgl_tpu.check`` (same escalation ladder,
    same resume-from-snapshot growth) — callers whose ``max_capacity``
    never crosses the threshold see byte-identical behavior.  Above it,
    the monolithic search runs with its ceiling clamped to the threshold;
    on capacity exhaustion the search splits (see the module docstring)
    instead of compiling ever-larger engines.  ``fission=None`` reads the
    ``JTPU_FISSION`` knob; ``threshold``/``max_subproblems`` default to
    their env knobs.  Remaining kwargs pass through to ``wgl_tpu.check``.
    """
    from jepsen_tpu.checker import wgl_tpu
    thr = threshold if threshold is not None else fission_threshold()
    enabled = fission if fission is not None else fission_enabled()
    if not enabled or history is None or max_capacity <= thr:
        return wgl_tpu.check(model, history, prepared=prepared,
                             capacity=capacity,
                             max_capacity=max_capacity, explain=explain,
                             **opts)
    _bump(checks=1)
    r = wgl_tpu.check(model, history, prepared=prepared,
                      capacity=min(capacity, thr),
                      max_capacity=thr, explain=explain, **opts)
    if not r.get("capacity-exceeded"):
        return r
    return split_check(model, history, capacity=capacity,
                       max_capacity=max_capacity, threshold=thr,
                       max_subproblems=max_subproblems, explain=explain,
                       base_explored=int(r.get("configs-explored", 0)),
                       **opts)


def split_check(model: JaxModel, history: History,
                capacity: int = 1024, max_capacity: int = 65536,
                threshold: Optional[int] = None,
                max_subproblems: Optional[int] = None,
                explain: bool = True, base_explored: int = 0,
                **opts: Any) -> Dict[str, Any]:
    """Split an already-overflowed search into sub-problems and recombine.

    Called by :func:`check` after its threshold-clamped monolithic run
    overflowed, and by ``parallel.batch.check_batch`` for lanes whose next
    escalation rung would cross the threshold.  Any internal failure
    degrades to the monolithic escalation path (the exact pre-fission
    behavior), never to a fabricated verdict."""
    thr = threshold if threshold is not None else fission_threshold()
    max_subs = (max_subproblems if max_subproblems is not None
                else fission_max_subproblems())
    _bump(splits=1)
    t0 = time.monotonic()
    try:
        subs = component_split(model, history)
        if subs is not None and len(subs) >= 2:
            res = _check_components(model, subs, threshold=thr,
                                    max_capacity=max_capacity,
                                    max_subproblems=max_subs,
                                    explain=explain,
                                    base_explored=base_explored, **opts)
        else:
            res = _ghost_split(model, history, capacity=capacity,
                               threshold=thr, max_capacity=max_capacity,
                               max_subproblems=max_subs, explain=explain,
                               base_explored=base_explored, **opts)
    except Exception as e:  # noqa: BLE001 — splitting must never lose a verdict
        _bump(errors=1)
        res = _escalate(model, history, capacity=capacity,
                        max_capacity=max_capacity, explain=explain,
                        why=f"fission error: {type(e).__name__}: {e}",
                        threshold=thr, **opts)
    dt = time.monotonic() - t0
    HISTS.observe("fission:split", dt)
    RECORDER.record("fission", "split", dur_s=dt,
                    args={"verdict": str(res.get("valid")),
                          "mode": (res.get("fission") or {}).get("mode")})
    return res


# ---------------------------------------------------------------------------
# Component split (P-compositionality)
# ---------------------------------------------------------------------------

def component_split(model: JaxModel,
                    history: History) -> Optional[List[History]]:
    """Partition a history into independent per-component sub-histories,
    or None when the model declares no independence / any op spans the
    whole object / everything lands in one component.

    Components are connected components of the "shares a key" relation
    over the model's ``components`` hook (union-find).  Each invoke and
    its completion travel together; ``fail`` pairs are dropped (they never
    took effect — prep.py removes them anyway), and unconstraining ops
    (hook returns an empty set) are elided: they are always linearizable
    and state-preserving, so they decide nothing in any component."""
    comp = getattr(model, "components", None)
    if comp is None:
        return None
    h = history.client_ops().complete()
    pairs = h.pair_index()

    parent: Dict[Any, Any] = {}

    def find(k):
        root = k
        while parent.get(root, root) != root:
            root = parent[root]
        while parent.get(k, k) != k:
            parent[k], k = root, parent[k]
        return root

    def union(a, b):
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[rb] = ra

    key_of: Dict[int, Any] = {}  # invoke position -> one of its keys
    for i, op in enumerate(h.ops):
        if op.type != INVOKE:
            continue
        j = int(pairs[i])
        ctype = h.ops[j].type if j >= 0 else INFO
        if ctype == FAIL:
            continue
        keys = comp(op)
        if keys is None:
            return None
        ks = sorted(keys, key=repr)
        if not ks:
            continue
        parent.setdefault(ks[0], ks[0])
        for k in ks[1:]:
            parent.setdefault(k, k)
            union(ks[0], k)
        key_of[i] = ks[0]

    groups: Dict[Any, List[int]] = {}
    order: List[Any] = []
    for i in sorted(key_of):
        root = find(key_of[i])
        if root not in groups:
            groups[root] = []
            order.append(root)
        groups[root].append(i)
        j = int(pairs[i])
        if j >= 0:
            groups[root].append(j)
    if len(order) < 2:
        return None
    return [History([h.ops[p] for p in sorted(groups[root])], reindex=True)
            for root in order]


def _check_components(model: JaxModel, subs: List[History], *,
                      threshold: int, max_capacity: int,
                      max_subproblems: int, explain: bool,
                      base_explored: int, **opts: Any) -> Dict[str, Any]:
    _bump(component_splits=1, component_subproblems=len(subs))
    RECORDER.record("fission", "component-split",
                    args={"subproblems": len(subs)})
    results = _dispatch_subproblems(model, subs, threshold=threshold)
    # A component can itself be too entangled for the threshold (e.g. all
    # its ghosts share one key): resolve each such lane with the ghost
    # case-split before recombining — components are already maximal, so
    # re-splitting by key cannot help.
    for i, r in enumerate(results):
        if r.get("valid") not in (True, False) and _exceeded(r):
            _bump(sub_overflows=1)
            results[i] = _ghost_split(
                model, subs[i], capacity=min(256, threshold),
                threshold=threshold, max_capacity=max_capacity,
                max_subproblems=max_subproblems, explain=explain,
                base_explored=0, **opts)
    return _recombine_components(model, subs, results, explain=explain,
                                 base_explored=base_explored)


def _recombine_components(model: JaxModel, subs: List[History],
                          results: List[Dict[str, Any]], *, explain: bool,
                          base_explored: int) -> Dict[str, Any]:
    _bump(recombines=1)
    explored = base_explored + sum(
        int(r.get("configs-explored", 0) or 0) for r in results)
    meta = {"mode": "components", "subproblems": len(subs)}
    for h, r in zip(subs, results):
        if r.get("valid") is False:
            # Locality: a refuted independent projection refutes the whole
            # history; the witness is re-derived on that sub-problem only.
            # witness: refuting op from the refuted sub-problem attached; CPU witness on that sub-history
            out = {"valid": False, "analyzer": ANALYZER,
                   "op": r.get("op"), "configs-explored": explored,
                   "fission": {**meta, "refuting-subproblem": True}}
            if "witness" in r:
                out["witness"] = r["witness"]
            elif explain and r.get("op") and model.cpu_model is not None:
                from jepsen_tpu.engine.witness import cpu_witness
                out["witness"] = cpu_witness(model, h,
                                             Op.from_dict(r["op"]))
            return out
    if all(r.get("valid") is True for r in results):
        return {"valid": True, "analyzer": ANALYZER,
                "configs-explored": explored, "fission": meta}
    errs = [r.get("error") for r in results
            if r.get("valid") not in (True, False)]
    return {"valid": "unknown", "analyzer": ANALYZER,
            "error": f"{len(errs)} fission sub-problem(s) indefinite: "
                     f"{errs[0]}",
            "configs-explored": explored, "fission": meta}


# ---------------------------------------------------------------------------
# Ghost case-split (decrease-and-conquer)
# ---------------------------------------------------------------------------

def _real_ghosts(model: JaxModel,
                 h: History) -> Optional[List[Tuple[int, int]]]:
    """Positions of (invoke, info-completion-or--1) pairs that actually
    constrain the search, in ``h`` (client ops, uncompleted).  Mirrors
    prep.py's elimination: a crashed pure read with an unknown operand
    never enters the pending window, so forcing it could only fabricate
    constraints — it is left in place for prepare to drop.  Returns None
    when the model cannot encode an op (fission then escalates)."""
    pairs = h.pair_index()
    pure_fs = set(model.pure_read_fs)
    ghosts: List[Tuple[int, int]] = []
    for i, op in enumerate(h.ops):
        if op.type != INVOKE:
            continue
        j = int(pairs[i])
        ctype = h.ops[j].type if j >= 0 else INFO
        if ctype != INFO:
            continue
        try:
            f, a, _b = model.encode_op(op)
        except Exception:  # noqa: BLE001 — undecodable op: leave history alone
            return None
        if pure_fs and f in pure_fs and a == UNKNOWN32:
            continue
        ghosts.append((i, j))
    return ghosts


def _fresh_process_base(h: History) -> int:
    return max((op.process for op in h.ops
                if isinstance(op.process, int)), default=0) + 1


def ghost_variant(h: History, ghosts: Sequence[Tuple[int, int]],
                  force_mask: int) -> History:
    """The ghost-free variant of ``h`` for one subset of its ghosts.

    Ghosts whose bit is clear in ``force_mask`` are *elided* (invoke and
    info completion dropped: the op never took effect); set bits are
    *forced*: the invoke stays in place under a fresh process id (process
    ids are reused after crashes — keeping the original would mis-pair
    with a later op of the same process once the info completion is gone)
    and an ok completion carrying the invoke's value is appended at stream
    end, i.e. "took effect somewhere between invocation and the end" —
    exactly the engines' ghost-linearization window."""
    fresh = _fresh_process_base(h)
    drop = set()
    forced: Dict[int, int] = {}
    for gi, (i, j) in enumerate(ghosts):
        if (force_mask >> gi) & 1:
            forced[i] = fresh + gi
            if j >= 0:
                drop.add(j)
        else:
            drop.add(i)
            if j >= 0:
                drop.add(j)
    out: List[Op] = []
    tail: List[Op] = []
    for pos, op in enumerate(h.ops):
        if pos in drop:
            continue
        if pos in forced:
            p = forced[pos]
            out.append(op.with_(process=p))
            tail.append(Op(process=p, type=OK, f=op.f, value=op.value))
        else:
            out.append(op)
    return History(out + tail, reindex=True)


def _ghost_split(model: JaxModel, history: History, *, capacity: int,
                 threshold: int, max_capacity: int, max_subproblems: int,
                 explain: bool, base_explored: int,
                 **opts: Any) -> Dict[str, Any]:
    from jepsen_tpu.checker import wgl_tpu
    h = history.client_ops()
    ghosts = _real_ghosts(model, h)
    if ghosts is None or not ghosts:
        return _escalate(model, history, capacity=capacity,
                         max_capacity=max_capacity, explain=explain,
                         why="no ghosts to split on",
                         threshold=threshold, **opts)
    k = len(ghosts)
    if (1 << k) > max_subproblems:
        return _escalate(model, history, capacity=capacity,
                         max_capacity=max_capacity, explain=explain,
                         why=f"2^{k} ghost variants exceed the "
                             f"{max_subproblems} sub-problem cap",
                         threshold=threshold, **opts)
    _bump(ghost_splits=1, ghost_subproblems=1 << k)
    RECORDER.record("fission", "ghost-split",
                    args={"ghosts": k, "variants": 1 << k})
    meta = {"mode": "ghosts", "ghosts": k, "subproblems": 1 << k}
    # The all-elided variant first: "no crashed op took effect" is the
    # common case, and a valid verdict short-circuits the disjunction.
    elided = ghost_variant(h, ghosts, 0)
    r0 = wgl_tpu.check(model, elided, capacity=min(capacity, threshold),
                       max_capacity=threshold, explain=explain, **opts)
    explored = base_explored + int(r0.get("configs-explored", 0) or 0)
    if r0.get("valid") is True:
        _bump(short_circuits=1, recombines=1)
        return {"valid": True, "analyzer": ANALYZER,
                "configs-explored": explored,
                "fission": {**meta, "short-circuit": True}}
    variants = [ghost_variant(h, ghosts, m) for m in range(1, 1 << k)]
    results = _dispatch_subproblems(model, variants, threshold=threshold)
    _bump(recombines=1)
    explored += sum(int(r.get("configs-explored", 0) or 0)
                    for r in results)
    for r in results:
        if r.get("valid") is True:
            return {"valid": True, "analyzer": ANALYZER,
                    "configs-explored": explored, "fission": meta}
    if r0.get("valid") is False and \
            all(r.get("valid") is False for r in results):
        # Every branch of the exact disjunction is refuted, so the history
        # is not linearizable under ANY crashed-op outcome.  The canonical
        # evidence is the all-elided branch's refutation (its witness was
        # re-derived on that sub-problem only).
        # witness: all 2^ghosts case-split branches refuted; all-elided branch's refuting op + witness attached
        out = {"valid": False, "analyzer": ANALYZER, "op": r0.get("op"),
               "configs-explored": explored, "fission": meta}
        if "witness" in r0:
            out["witness"] = r0["witness"]
        return out
    # Indefinite branches and no valid one: the disjunction cannot
    # conclude — fall back to the pre-fission behavior (escalate the
    # monolithic search to the caller's real ceiling; unknown, never
    # false, if that overflows too).
    return _escalate(model, history, capacity=capacity,
                     max_capacity=max_capacity, explain=explain,
                     why="ghost case-split indefinite",
                     threshold=threshold, **opts)


# ---------------------------------------------------------------------------
# Sub-problem dispatch + escalation
# ---------------------------------------------------------------------------

def _exceeded(r: Dict[str, Any]) -> bool:
    return bool(r.get("capacity-exceeded")) \
        or "capacity exceeded" in str(r.get("error", ""))


def subproblem_floors(subs: Sequence[History]) -> Tuple[int, int]:
    """The shared (window, events) bucket floors for one sub-problem
    dispatch — every lane rides the same compiled shape, and both floors
    are ladder images (never raw history shapes): the TRACE02 seam the
    trace lint runs the real derivation through."""
    from jepsen_tpu.engine import ladder
    return (max(ladder.width_bucket(h) for h in subs),
            max(ladder.events_bucket(h) for h in subs))


def _dispatch_subproblems(model: JaxModel, subs: Sequence[History], *,
                          threshold: int) -> List[Dict[str, Any]]:
    """Run sub-problems as ordinary engine-substrate lanes.

    Shapes are bucket-derived (SHAPE01): one shared window/events floor
    over the sub-problems keeps every dispatch on the ladder.  Small
    sub-problem swarms route through megabatch (continuous refill eats
    hundreds of tiny lanes); the rest run as plain batch lanes.  Both run
    with fission pinned OFF and the threshold as their capacity ceiling,
    so a sub-problem can never re-split or out-escalate its parent."""
    t0 = time.monotonic()
    w_floor, ev_floor = subproblem_floors(subs)
    from jepsen_tpu.parallel.megabatch import megabatch_enabled
    if len(subs) >= 4 and megabatch_enabled() \
            and ev_floor <= _mega_events_max():
        from jepsen_tpu.parallel.megabatch import check_megabatch
        from jepsen_tpu.serve.buckets import mega_lane_bucket
        out = check_megabatch(model, list(subs), max_capacity=threshold,
                              window_floor=w_floor, ev_floor=ev_floor,
                              lanes=mega_lane_bucket(len(subs)))
    else:
        from jepsen_tpu.parallel.batch import check_batch
        out = check_batch(model, list(subs),
                          capacity=min(256, threshold),
                          max_capacity=threshold,
                          window_floor=w_floor, fission=False)
    dt = time.monotonic() - t0
    HISTS.observe("fission:subdispatch", dt)
    RECORDER.record("fission", "subdispatch", dur_s=dt,
                    args={"lanes": len(subs), "ev_floor": ev_floor,
                          "w_floor": w_floor})
    return out


def _mega_events_max() -> int:
    from jepsen_tpu.serve.buckets import MEGA_EVENTS_MAX
    return MEGA_EVENTS_MAX


def _escalate(model: JaxModel, history: History, *, capacity: int,
              max_capacity: int, explain: bool, why: str,
              threshold: Optional[int] = None,
              **opts: Any) -> Dict[str, Any]:
    """The pre-fission behavior: escalate the monolithic frontier to the
    caller's real ceiling.  Taken only when neither splitter applies or
    the split could not decide — fission never returns a worse verdict
    than the escalation ladder would have.  When even the real ceiling
    overflows and a ``threshold`` is known, the window-shrinking recursion
    (engine.shrink, arXiv 2410.04581) gets one last shot at a refutation
    on threshold-sized prefixes; its False-or-unknown envelope means this
    can only improve the verdict, never change a concluded one."""
    from jepsen_tpu.checker import wgl_tpu
    _bump(escalations=1)
    RECORDER.record("fission", "escalate", args={"why": why})
    t0 = time.monotonic()
    res = wgl_tpu.check(model, history, capacity=capacity,
                        max_capacity=max_capacity, explain=explain, **opts)
    HISTS.observe("fission:escalate", time.monotonic() - t0)
    res.setdefault("fission", {"mode": "escalate", "why": why})
    if threshold is not None and res.get("valid") not in (True, False) \
            and (res.get("capacity-exceeded")
                 or "capacity exceeded" in str(res.get("error", ""))):
        from jepsen_tpu.engine import shrink
        if shrink.shrink_enabled():
            sres = shrink.shrink_check(model, history, threshold=threshold,
                                       capacity=min(capacity, threshold),
                                       explain=explain, **opts)
            if sres.get("valid") is False:
                # witness: shrink refutation carries the refuting prefix's op + witness (engine.shrink soundness)
                sres["fission"]["escalate-why"] = why
                return sres
    return res
