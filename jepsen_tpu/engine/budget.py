"""Deadline/budget plumbing shared by every engine driver.

One discipline, three consumers (wgl batch drivers, the elle engine's
per-lane CPU finishes, the monitor's epoch checks): a caller's
``budget_s`` becomes a monotonic deadline once at the call boundary, and
everything downstream asks the deadline for *remaining* time — so
budgets compose across fan-out (every lane of a group shares the call's
one clock) and a wedged stage can never grant its successors more time
than the caller had.  Exhaustion degrades a verdict to ``unknown``,
never to false (the SOUND01 contract).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional


class Deadline:
    """A monotonic deadline with remaining-time queries.

    ``Deadline.after(None)`` is the unbounded deadline: ``remaining()``
    is None, ``expired()`` is False — callers thread one object through
    either way instead of forking on "was a budget set"."""

    __slots__ = ("at",)

    def __init__(self, at: Optional[float]):
        self.at = at

    @classmethod
    def after(cls, budget_s: Optional[float]) -> "Deadline":
        return cls(None if budget_s is None
                   else time.monotonic() + float(budget_s))

    def remaining(self) -> Optional[float]:
        if self.at is None:
            return None
        return max(0.0, self.at - time.monotonic())

    def expired(self) -> bool:
        return self.at is not None and time.monotonic() >= self.at

    def search_budget(self):
        """The elle cycle-search budget pinned to this deadline (None
        when unbounded): every lane's host-side witness search shares
        the call's one clock."""
        if self.at is None:
            return None
        from jepsen_tpu.elle.graph import SearchBudget
        return SearchBudget(deadline_s=self.remaining())


def exhausted_result(analyzer: str, what: str,
                     **extra: Any) -> Dict[str, Any]:
    """The canonical budget/capacity-exhaustion verdict: ``unknown`` with
    the exhausted resource named — never a fabricated false (SOUND01)."""
    return {"valid": "unknown", "analyzer": analyzer, "error": what,
            **extra}
