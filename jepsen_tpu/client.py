"""Client protocol — how a workload talks to the system under test.

Parity: jepsen.client (jepsen/src/jepsen/client.clj:9-27): a Client is opened
for a node, set up once, invoked with ops, torn down, and closed.  Clients
are single-threaded: each logical process owns one client instance; when a
process crashes the interpreter opens a fresh client for its successor
process unless the client is Reusable (client.clj:29).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from jepsen_tpu.history import Op


class Client:
    """One logical process's connection to the system under test."""

    def open(self, test: Dict[str, Any], node: str) -> "Client":
        """Return a client bound to ``node`` (fresh instance or self)."""
        return self

    def setup(self, test: Dict[str, Any]) -> None:
        """One-time data setup (create tables, etc.)."""

    def invoke(self, test: Dict[str, Any], op: Op) -> Op:
        """Apply ``op``; return its completion (type ok/fail/info)."""
        raise NotImplementedError

    def teardown(self, test: Dict[str, Any]) -> None:
        """Undo setup."""

    def close(self, test: Dict[str, Any]) -> None:
        """Release the connection."""

    # -- optional: Reusable (client.clj:29) -------------------------------
    reusable = False


class NoopClient(Client):
    """Completes every op ok without talking to anything (client.clj:46)."""

    def invoke(self, test, op):
        return op.with_(type="ok")


noop = NoopClient


class ValidatingClient(Client):
    """Wraps a client, asserting protocol contracts at runtime
    (client.clj:64-114): completions must be completions of the invocation
    (same process/f), with a legal type."""

    def __init__(self, inner: Client):
        self.inner = inner

    def open(self, test, node):
        return ValidatingClient(self.inner.open(test, node))

    def setup(self, test):
        self.inner.setup(test)

    def invoke(self, test, op):
        res = self.inner.invoke(test, op)
        if not isinstance(res, Op):
            raise RuntimeError(
                f"client invoke returned {res!r}, not an Op, for {op!r}")
        if res.type not in ("ok", "fail", "info"):
            raise RuntimeError(
                f"client completion has illegal type {res.type!r}: {res!r}")
        if res.process != op.process or res.f != op.f:
            raise RuntimeError(
                f"client completion {res!r} does not match invocation {op!r}")
        return res

    def teardown(self, test):
        self.inner.teardown(test)

    def close(self, test):
        self.inner.close(test)

    @property
    def reusable(self):
        return self.inner.reusable


def validate(client: Client) -> Client:
    return ValidatingClient(client)


class ClosedClient(Client):
    """Placeholder for a client that has been closed; any use is a bug."""

    def invoke(self, test, op):
        raise RuntimeError("client is closed")
