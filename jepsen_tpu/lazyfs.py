"""lazyfs integration — lose-unfsynced-writes faults via a FUSE filesystem.

Parity: jepsen.lazyfs (jepsen/src/jepsen/lazyfs.clj): clone and build the
lazyfs C++ FUSE filesystem on each node at a pinned commit (lazyfs.clj:23-29),
mount a directory through it, and drive faults through its fifo command
channel — ``lose-unfsynced-writes!`` (243) and ``checkpoint!`` (253).
Includes the DB wrapper and nemesis (224, 262).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, Optional

from jepsen_tpu import db as jdb
from jepsen_tpu.control import Session, session
from jepsen_tpu.control import util as cu
from jepsen_tpu.history import Op
from jepsen_tpu.nemesis import Nemesis

REPO = "https://github.com/dsrhaslab/lazyfs.git"
COMMIT = "2902807a2b7a9c0e9a69d8a4e39b9d95e6e57d1b"  # pinned like lazyfs.clj
DIR = "/opt/jepsen-tpu/lazyfs"


@dataclass
class LazyFS:
    """A directory mounted through lazyfs on a node."""

    mount_dir: str
    data_dir: Optional[str] = None
    fifo: Optional[str] = None

    def __post_init__(self):
        base = self.mount_dir.rstrip("/")
        self.data_dir = self.data_dir or base + ".root"
        self.fifo = self.fifo or base + ".fifo"


def install(test, node) -> None:
    """Clone + build lazyfs (build-on-node, lazyfs.clj:23-49)."""
    s = session(test, node).sudo()
    if cu.exists(s, f"{DIR}/lazyfs/build/lazyfs"):
        return
    s.env(DEBIAN_FRONTEND="noninteractive").exec(
        "apt-get", "install", "-y", "git", "g++", "cmake", "libfuse3-dev",
        "fuse3")
    s.exec("rm", "-rf", DIR)
    s.exec("git", "clone", REPO, DIR)
    s.cd(DIR).exec("git", "checkout", COMMIT)
    s.cd(f"{DIR}/libs/libpcache").exec("./build.sh")
    s.cd(f"{DIR}/lazyfs").exec("./build.sh")


def config(fs: LazyFS) -> str:
    return (f"[faults]\nfifo_path=\"{fs.fifo}\"\n"
            "[cache]\napply_lru_eviction=false\n"
            "[cache.simple]\ncustom_size=\"1gb\"\nblocks_per_page=1\n")


def mount(test, node, fs: LazyFS) -> None:
    s = session(test, node).sudo()
    cfg = f"{fs.mount_dir.rstrip('/')}.lazyfs.toml"
    s.exec("mkdir", "-p", fs.mount_dir, fs.data_dir)
    cu.write_file(s, config(fs), cfg)
    cu.start_daemon(
        s, f"{DIR}/lazyfs/build/lazyfs", fs.mount_dir,
        "--config-path", cfg, "-o", "allow_other", "-o", "modules=subdir",
        "-o", f"subdir={fs.data_dir}", "-f",
        pidfile=fs.mount_dir.rstrip("/") + ".pid",
        logfile=fs.mount_dir.rstrip("/") + ".log")


def umount(test, node, fs: LazyFS) -> None:
    s = session(test, node).sudo()
    s.exec_result("fusermount3", "-u", fs.mount_dir)
    cu.stop_daemon(s, fs.mount_dir.rstrip("/") + ".pid")


def fifo_command(test, node, fs: LazyFS, cmd: str) -> None:
    """Write a command into the lazyfs fifo (lazyfs.clj:218-224)."""
    s = session(test, node).sudo()
    s.exec("bash", "-c", f"echo {cmd} > {fs.fifo}")


def lose_unfsynced_writes(test, node, fs: LazyFS) -> None:
    """Drop every page not yet fsynced (lazyfs.clj:243)."""
    fifo_command(test, node, fs, "lazyfs::clear-cache")


def checkpoint(test, node, fs: LazyFS) -> None:
    """Flush everything to disk (lazyfs.clj:253)."""
    fifo_command(test, node, fs, "lazyfs::cache-checkpoint")


class LazyFSDB(jdb.DB):
    """Wrap a DB so its data dir lives on lazyfs (lazyfs.clj:224)."""

    def __init__(self, inner: jdb.DB, fs: LazyFS):
        self.inner = inner
        self.fs = fs

    def setup(self, test, node):
        install(test, node)
        mount(test, node, self.fs)
        self.inner.setup(test, node)

    def teardown(self, test, node):
        self.inner.teardown(test, node)
        umount(test, node, self.fs)


class LazyFSNemesis(Nemesis):
    """Drives lose-unfsynced-writes / checkpoint ops (lazyfs.clj:262)."""

    def __init__(self, lazy_fs: LazyFS):
        self.lazy_fs = lazy_fs

    def invoke(self, test, op: Op) -> Op:
        from jepsen_tpu.nemesis.faults import pick_nodes
        targets = pick_nodes(test, op.value)
        if op.f == "lose-unfsynced-writes":
            for n in targets:
                lose_unfsynced_writes(test, n, self.lazy_fs)
        elif op.f == "checkpoint":
            for n in targets:
                checkpoint(test, n, self.lazy_fs)
        else:
            raise ValueError(f"lazyfs nemesis doesn't handle f={op.f!r}")
        return op.with_(type="info", value=sorted(targets))

    def fs(self):
        return ["lose-unfsynced-writes", "checkpoint"]
