"""Benchmark: linearizability-check wall-clock on a 10k-op CAS history.

North star (BASELINE.md): the reference's CPU knossos search times out on
10k-op CAS-register histories; target is a verdict in <60 s on TPU.  This
bench synthesizes a 10k-op history (fixed seed, linearizable by
construction, with crashes so indeterminate ops stay pending), warms the
engine on a small history (compile excluded, as for any cached-jit system),
then times the device check.  ``vs_baseline`` is 60 s / measured (>1 beats
the target).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

N_OPS = 10_000
BASELINE_S = 60.0
# 512 halves wall-clock vs 256 on the tunneled device (fewer chunk-boundary
# host polls) while keeping capacity adaptation tight enough for this
# workload's crash-bursts.
CHUNK = 512


def main():
    t_setup = time.time()
    from jepsen_tpu.checker import wgl_tpu
    from jepsen_tpu.checker.prep import prepare
    from jepsen_tpu.models import get_model
    from jepsen_tpu.synth import cas_register_history

    model = get_model("cas-register")

    # Main history: ~6 crashed ops over 10k — realistic for a register
    # workload (each forever-pending crashed mutation doubles the reachable
    # configuration set, so crash count is the capacity driver).
    big = cas_register_history(N_OPS, concurrency=8, crash_p=0.0003, seed=2026)
    prep = prepare(big, model)
    window = wgl_tpu._round_window(prep.window)
    # Warm-up: compile the engine at the starting capacity and every
    # escalation step the driver can reach, so a mid-run overflow resume
    # pays no compile (as for any cached-jit system).
    small = cas_register_history(200, concurrency=8, crash_p=0.005, seed=7)
    for cap in (1024, 4096, 16384):
        r = wgl_tpu.check(model, small,
                          prepared=_pad_window(prepare(small, model), window),
                          capacity=cap, chunk=CHUNK)
        assert r["valid"] is True, r
    setup_s = time.time() - t_setup

    # max_capacity matches the largest warmed engine, so the timed region
    # can never hit an unwarmed compile (this seed's peak need is ~9k).
    # Two timed runs, best-of reported: the device is behind a tunnel and
    # a single transfer stall would otherwise double the reading.
    runs = []
    for _ in range(2):
        t0 = time.time()
        r = wgl_tpu.check(model, big, prepared=prep, capacity=1024,
                          chunk=CHUNK, max_capacity=16384)
        runs.append(round(time.time() - t0, 3))
        assert r["valid"] is True, r
    wall = min(runs)

    print(json.dumps({
        "metric": "cas_register_10k_op_linearizability_check_wall_s",
        "value": round(wall, 3),
        "unit": "s",
        "vs_baseline": round(BASELINE_S / wall, 2),
        "extra": {
            "n_ops": N_OPS,
            "events": int(len(prep)),
            "timing": "min-of-2",   # all runs in "runs"; a tunnel stall
            "runs": runs,           # would otherwise double the reading
            "chunk": CHUNK,
            "window": int(prep.window),
            "configs_explored": int(r.get("configs-explored", -1)),
            "setup_and_compile_s": round(setup_s, 1),
            "analyzer": r.get("analyzer"),
        },
    }))


def _pad_window(prep, window):
    """Return prep unchanged but claiming `window` slots so the warm-up
    compiles the same engine shape as the real run."""
    prep.window = max(prep.window, window)
    return prep


if __name__ == "__main__":
    sys.exit(main())
