"""Benchmark: TPU linearizability engine vs the measured CPU baseline.

North star (BASELINE.md): the reference's CPU knossos search dies on 10k-op
CAS-register histories; target <60 s on TPU.  No published CPU figure exists,
so this bench *measures* the CPU tier (wgl_cpu, the knossos-role oracle) on
200 / 1k / 10k-op histories under a timeout, and reports the device tiers:

  easy     10k ops, window ~12            (round-1 headline, comparability)
  hard     10k ops, window >= 64, crash-heavy: capacity escalation territory
  ceiling  ghost-write burst that must blow past max capacity: clean,
           *timed* degradation to an unknown verdict at the 65536 ceiling
  refuted  10k ops with corrupted reads: early-exit on the failing prefix
  batch    check_batch throughput over short per-key histories -> hist/sec

Headline value = MEDIAN of the easy-tier runs (all runs disclosed);
vs_baseline = measured CPU 10k wall / device wall (a lower bound when the
CPU run timed out — flagged in extras).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
Env: JTPU_BENCH_SMOKE=1 shrinks every tier for a CPU-backend smoke run.
"""

import json
import os
import statistics
import subprocess
import sys
import threading
import time

SMOKE = bool(os.environ.get("JTPU_BENCH_SMOKE"))

N_OPS = 600 if SMOKE else 10_000
CPU_TIMEOUT_S = 20.0 if SMOKE else 300.0
TARGET_S = 60.0
CHUNK = 512
BATCH_N = 16 if SMOKE else 96
BATCH_OPS = 200


def progress(msg: str) -> None:
    """Phase marker on stderr so a long bench run is diagnosable live (the
    JSON contract allows only the one final stdout line)."""
    print(f"[bench +{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


def timed_runs(fn, n):
    runs = []
    for _ in range(n):
        t0 = time.time()
        r = fn()
        runs.append(round(time.time() - t0, 3))
    return r, runs


def cpu_tier(model_cpu, histories):
    """Measure the CPU oracle on each history with a hard timeout — this is
    the 'CPU knossos' baseline the device tier is claimed against."""
    from jepsen_tpu.checker import wgl_cpu
    out = {}
    for name, h in histories.items():
        cancel = threading.Event()
        timer = threading.Timer(CPU_TIMEOUT_S, cancel.set)
        timer.start()
        t0 = time.time()
        try:
            r = wgl_cpu.check(model_cpu, h, cancel=cancel)
            out[name] = {"wall_s": round(time.time() - t0, 3),
                         "valid": r["valid"],
                         "configs_explored": r.get("configs-explored")}
        except wgl_cpu.Cancelled:
            out[name] = {"wall_s": round(time.time() - t0, 3),
                         "timeout": True, "timeout_s": CPU_TIMEOUT_S}
        except wgl_cpu.SearchExploded as e:
            out[name] = {"wall_s": round(time.time() - t0, 3),
                         "exploded_at": e.n}
        finally:
            timer.cancel()
    return out


def second_process_setup():
    """Time a fresh process warming one engine shape: with the persistent
    compilation cache this is a disk load, not a recompile."""
    code = (
        "import time; t0=time.time()\n"
        "from jepsen_tpu.checker import wgl_tpu\n"
        "from jepsen_tpu.models import get_model\n"
        "from jepsen_tpu.synth import cas_register_history\n"
        "m = get_model('cas-register')\n"
        "h = cas_register_history(200, concurrency=8, crash_p=0.005, seed=7)\n"
        "r = wgl_tpu.check(m, h, capacity=1024, chunk=%d)\n"
        "assert r['valid'] is True\n"
        "print('SETUP_S', round(time.time()-t0, 1))\n" % CHUNK)
    try:
        p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=600,
                           cwd=os.path.dirname(os.path.abspath(__file__)))
        for line in p.stdout.splitlines():
            if line.startswith("SETUP_S"):
                return float(line.split()[1])
        print("second_process_setup failed rc=%d: %s"
              % (p.returncode, p.stderr[-2000:]), file=sys.stderr)
    except subprocess.TimeoutExpired:
        print("second_process_setup timed out", file=sys.stderr)
    return None


def main():
    t_setup = time.time()
    from jepsen_tpu.checker import wgl_tpu
    from jepsen_tpu.checker.prep import prepare
    from jepsen_tpu.models import CASRegister, get_model
    from jepsen_tpu.parallel.batch import check_batch
    from jepsen_tpu.synth import (cas_register_history, corrupt_reads,
                                  doomed_cas_padding, ghost_write_burst)
    from jepsen_tpu.history import History

    model = get_model("cas-register")

    # --- histories ---------------------------------------------------------
    easy = cas_register_history(N_OPS, concurrency=8, crash_p=0.0003,
                                seed=2026)
    # Hard tier: 48 never-linearizable crashed CAS ops pin the window >= 64
    # (per-round cost is O(capacity * window)), and crashes drive capacity
    # escalation (each pending crashed write doubles the reachable
    # configuration set) — sized so the search still CONCLUDES below the
    # ceiling; unbounded ghost pileups get their own ceiling tier below.
    n_pad, hard_conc = (16, 8) if SMOKE else (48, 10)
    pad = doomed_cas_padding(n_pad)
    hard_work = cas_register_history(N_OPS, concurrency=hard_conc,
                                     crash_p=0.0008, seed=11)
    hard = History(pad + list(hard_work), reindex=True)
    # Ceiling tier: 18 pending ghost writes need >= 2^18 configurations —
    # past any ceiling here; measures how fast the engine escalates through
    # the whole capacity ladder and degrades cleanly to unknown.
    ceiling = History(
        ghost_write_burst(4 if SMOKE else 18)
        + list(cas_register_history(200, concurrency=4, crash_p=0.0,
                                    seed=3)),
        reindex=True)
    refuted = corrupt_reads(
        cas_register_history(N_OPS, concurrency=8, crash_p=0.0005, seed=4),
        n=2, seed=4)

    prep_easy = prepare(easy, model)
    prep_hard = prepare(hard, model)
    prep_ceiling = prepare(ceiling, model)
    prep_refuted = prepare(refuted, model)

    # --- warm-up: compile each engine shape the tiers can reach ------------
    progress("warm-up compiles")
    warm = cas_register_history(200, concurrency=8, crash_p=0.005, seed=7)
    for prep in (prep_easy, prep_hard, prep_ceiling, prep_refuted):
        window = wgl_tpu._round_window(prep.window)
        wp = prepare(warm, model)
        wp.window = max(wp.window, window)
        for cap in (1024, 4096) if SMOKE else (1024, 4096, 16384, 65536):
            r = wgl_tpu.check(model, warm, prepared=wp, capacity=cap,
                              chunk=CHUNK)
            assert r["valid"] is True, r
    batch_hs = [cas_register_history(BATCH_OPS, concurrency=6, crash_p=0.005,
                                     seed=100 + i) for i in range(BATCH_N)]
    for i in range(0, BATCH_N, 4):  # quarter refuted: mixed verdict stream
        batch_hs[i] = corrupt_reads(batch_hs[i], n=1, seed=i)
    # Warm at full batch size: jit keys on the leading batch dim, so a
    # partial warm-up would leave a compile inside the timed region.
    check_batch(model, batch_hs)
    setup_s = round(time.time() - t_setup, 1)

    # --- CPU baseline (measured, this machine) -----------------------------
    progress(f"cpu baseline (timeout {CPU_TIMEOUT_S:.0f}s per size)")
    cpu = cpu_tier(CASRegister(), {
        "200": cas_register_history(200, concurrency=8, crash_p=0.003,
                                    seed=1),
        "1k": cas_register_history(1000, concurrency=8, crash_p=0.001,
                                   seed=2),
        "10k": easy,
    })

    # --- device tiers ------------------------------------------------------
    easy_cap, hard_cap = (4096, 4096) if SMOKE else (16384, 65536)
    progress("easy tier")
    r_easy, easy_runs = timed_runs(
        lambda: wgl_tpu.check(model, easy, prepared=prep_easy, capacity=1024,
                              chunk=CHUNK, max_capacity=easy_cap), 3)
    assert r_easy["valid"] is True, r_easy
    progress("hard tier")
    r_hard, hard_runs = timed_runs(
        lambda: wgl_tpu.check(model, hard, prepared=prep_hard, capacity=1024,
                              chunk=CHUNK, max_capacity=hard_cap), 2)
    progress("ceiling tier")
    r_ceil, ceil_runs = timed_runs(
        lambda: wgl_tpu.check(model, ceiling, prepared=prep_ceiling,
                              capacity=1024, chunk=CHUNK,
                              max_capacity=hard_cap), 1)
    if not SMOKE:
        assert r_ceil["valid"] == "unknown", r_ceil
    progress("refuted tier")
    r_ref, ref_runs = timed_runs(
        lambda: wgl_tpu.check(model, refuted, prepared=prep_refuted,
                              capacity=1024, chunk=CHUNK, explain=False), 2)
    assert r_ref["valid"] is False, r_ref

    progress("batch tier")
    t0 = time.time()
    batch_res = check_batch(model, batch_hs)
    batch_wall = time.time() - t0
    n_false = sum(1 for r in batch_res if r["valid"] is False)
    assert n_false == BATCH_N // 4, [r["valid"] for r in batch_res]

    progress("second-process setup probe")
    setup2_s = second_process_setup()

    wall = statistics.median(easy_runs)
    cpu10k = cpu["10k"]
    cpu_wall = cpu10k["wall_s"]
    vs_lower_bound = bool(cpu10k.get("timeout") or cpu10k.get("exploded_at"))

    print(json.dumps({
        "metric": "cas_register_10k_op_linearizability_check_wall_s",
        "value": round(wall, 3),
        "unit": "s",
        "vs_baseline": round(cpu_wall / wall, 2),
        "extra": {
            "n_ops": N_OPS,
            "timing": "median-of-3",
            "vs_baseline_is_lower_bound": vs_lower_bound,
            "vs_target_60s": round(TARGET_S / wall, 2),
            "cpu_baseline": cpu,
            "easy": {"runs": easy_runs, "window": prep_easy.window,
                     "configs_explored": r_easy.get("configs-explored"),
                     "max_capacity_reached": r_easy.get(
                         "max-capacity-reached")},
            "hard": {"runs": hard_runs, "window": prep_hard.window,
                     "valid": r_hard["valid"],
                     "configs_explored": r_hard.get("configs-explored"),
                     "max_capacity_reached": r_hard.get(
                         "max-capacity-reached"),
                     "error": r_hard.get("error")},
            "ceiling": {"runs": ceil_runs, "window": prep_ceiling.window,
                        "valid": r_ceil["valid"],
                        "configs_explored": r_ceil.get("configs-explored"),
                        "error": r_ceil.get("error")},
            "refuted": {"runs": ref_runs,
                        "failed_op_index": r_ref["op"]["index"],
                        "configs_explored": r_ref.get("configs-explored")},
            "batch": {"n_histories": BATCH_N, "ops_each": BATCH_OPS,
                      "wall_s": round(batch_wall, 3),
                      "histories_per_sec": round(BATCH_N / batch_wall, 1)},
            "chunk": CHUNK,
            "setup_and_compile_s": setup_s,
            "second_process_setup_s": setup2_s,
            "analyzer": "wgl-tpu",
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
