"""Benchmark: TPU linearizability engine vs the measured CPU baseline.

North star (BASELINE.md): the reference's CPU knossos search dies on 10k-op
CAS-register histories; target <60 s on TPU.  No published CPU figure exists,
so this bench *measures* the CPU tier (wgl_cpu, the knossos-role oracle) on
200 / 1k / 10k-op histories under a timeout, and reports the device tiers:

  easy      10k ops, window ~12           (round-1 headline, comparability)
  hard      10k ops, window >= 64, crash-heavy: capacity escalation territory
  ceiling   ghost-write burst that must blow past max capacity: clean,
            *timed* degradation to an unknown verdict at the 65536 ceiling
  refuted   10k ops with corrupted reads: early-exit on the failing prefix
  batch     megabatch throughput over short per-key histories -> hist/sec
            (continuous-refill pipeline, parallel/megabatch.py), plus the
            same-host CPU-oracle comparison (per core AND per socket),
            lane-for-lane verdict parity on the sampled lanes, and the
            break-even core count, on two shapes (96 and 512 lanes)
  batch_sweep  histories/sec vs batch size (96/512/2048/8192) through the
            megabatch path — the throughput trajectory, tracked like the
            headline
  ablation  ghost-subsumption on vs off (JTPU_SUBSUME=0) on a ghost burst
            that concludes in O(crashes) configs with subsumption and needs
            ~2^crashes without — the measured evidence for the claim in
            checker/wgl_tpu.py:22-32
  sched     generator scheduler throughput (pure mix + wrapped stack),
            the committed record behind the ~24k ops/s claim
  multireg  10k-op multi-key register history (BASELINE configs #4/#5) on
            the device-tier MultiRegister vs the host oracle
  elle      transactional-anomaly engine (elle_tpu) on a 96 x 200-op
            list-append batch, parity-checked lane-by-lane against the CPU
            elle oracle, with the same device-vs-socket comparison as batch
  obs       observability toll: the same warmed serving campaign with the
            flight recorder off vs on (budget: <2% overhead), plus nonzero
            p50/p99 on the enqueue→dispatch / dispatch→verdict histograms;
            the same shape for the Watchtower telemetry plane (push
            cadence off vs on through a ProcFleet, budget: <2%), and the
            monitor's epoch spans must land in the merged Perfetto export

**Isolation:** every tier runs in its own subprocess with its own timeout; a
tier that crashes the TPU worker (or hangs) degrades to a per-tier
``{"status": "crashed"|"timeout"}`` entry and can never zero the artifact —
the round-2 bench died in shared warm-up and shipped no number at all.
Compiles amortize across the subprocesses via the persistent compilation
cache (jepsen_tpu/ops/cache.py).

Headline value = MEDIAN of the easy-tier runs (all runs disclosed);
vs_baseline = measured CPU 10k wall / device wall (a lower bound when the
CPU run timed out — flagged in extras).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
Env: JTPU_BENCH_SMOKE=1 shrinks every tier for a CPU-backend smoke run.
"""

import argparse
import json
import os
import statistics
import subprocess
import sys
import threading
import time

SMOKE = bool(os.environ.get("JTPU_BENCH_SMOKE"))

N_OPS = 600 if SMOKE else 10_000
CPU_TIMEOUT_S = 20.0 if SMOKE else 300.0
TARGET_S = 60.0
BATCH_N = 16 if SMOKE else 96
BATCH_OPS = 200
RESULT_TAG = "JTPU_TIER_RESULT "

# Per-tier wall-clock budgets (orchestrator kills a tier past its budget and
# records status=timeout instead of hanging the whole artifact).
TIER_TIMEOUT_S = {
    "easy": 300 if SMOKE else 1500,
    "cpu": 120 if SMOKE else 1100,
    "hard": 300 if SMOKE else 2400,
    # Cold-cache ladder warm-up measured 1466 s (the 65536 engine's
    # compile); with the persistent cache it is ~48 s.  Budget for cold.
    "ceiling": 300 if SMOKE else 2400,
    "refuted": 300 if SMOKE else 1200,
    "batch": 300 if SMOKE else 1200,
    "batch_sweep": 420 if SMOKE else 1800,
    "ablation_on": 300 if SMOKE else 900,
    "ablation_off": 300 if SMOKE else 900,
    "setup2": 300 if SMOKE else 700,
    "sched": 120 if SMOKE else 300,
    "multireg": 300 if SMOKE else 1500,
    "elle": 300 if SMOKE else 1200,
    "models": 300 if SMOKE else 900,
    "fleet": 300 if SMOKE else 900,
    "procfleet": 420 if SMOKE else 1200,
    "obs": 300 if SMOKE else 900,
    "elastic": 300 if SMOKE else 900,
    "fleetfission": 420 if SMOKE else 1200,
    "stream": 300 if SMOKE else 900,
}


def progress(msg: str) -> None:
    """Phase marker on stderr so a long bench run is diagnosable live (the
    JSON contract allows only the one final stdout line)."""
    print(f"[bench +{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


def timed_runs(fn, n):
    runs = []
    for _ in range(n):
        t0 = time.time()
        r = fn()
        runs.append(round(time.time() - t0, 3))
    return r, runs


def emit(data: dict) -> None:
    """Tier-worker result line (stdout; orchestrator greps for the tag)."""
    print(RESULT_TAG + json.dumps(data), flush=True)


# ---------------------------------------------------------------------------
# Shared history builders (deterministic — workers rebuild identical inputs)
# ---------------------------------------------------------------------------


def build_easy():
    from jepsen_tpu.synth import cas_register_history
    return cas_register_history(N_OPS, concurrency=8, crash_p=0.0003,
                                seed=2026)


def build_hard():
    # 48 never-linearizable crashed CAS ops pin the window >= 64 (per-round
    # cost is O(capacity * window)), and crashes drive capacity escalation
    # (each pending crashed write doubles the reachable configuration set)
    # — sized so the search still CONCLUDES below the ceiling; unbounded
    # ghost pileups get their own ceiling tier.  Concurrency 8 (round 2
    # used 10): measured on hardware, the conc-10 variant pins the engine
    # at capacity >= 16384 for most of the stream and overflows into 65536
    # at its worst burst — a tier that cannot finish inside any sane bench
    # budget.  Conc 8 keeps the same shape (wide window, escalation, ghost
    # bursts) with a ~4x smaller live-mask state space.
    from jepsen_tpu.history import History
    from jepsen_tpu.synth import cas_register_history, doomed_cas_padding
    n_pad, conc = (16, 8) if SMOKE else (48, 8)
    pad = doomed_cas_padding(n_pad)
    work = cas_register_history(N_OPS, concurrency=conc, crash_p=0.0008,
                                seed=11)
    return History(pad + list(work), reindex=True)


def build_ceiling():
    # 18 crashed adds on a grow-only BITSET: the linearized subset IS the
    # state, so the 2^18 configurations are genuinely distinct — neither
    # ghost-class canonicalization nor subset subsumption can merge them
    # (a register can't play this role: its state only remembers the last
    # value, so subsumption collapses any crashed-write pileup to an O(k)
    # antichain — which is exactly what the round-4 delta closure started
    # exploiting, obsoleting the old register-based ceiling history).
    # This blows past every capacity here and measures how fast the engine
    # escalates the whole ladder and degrades cleanly to unknown.
    from jepsen_tpu.synth import bitset_ceiling_history
    return bitset_ceiling_history(4 if SMOKE else 18, n_clean=200,
                                  concurrency=4)


def build_refuted():
    # Corruption lands in the first 15% of the stream so the tier can
    # *assert* the engine's early exit touched a bounded prefix (the
    # host-poll early-out claimed in wgl_tpu's module docs).
    from jepsen_tpu.synth import cas_register_history, corrupt_reads
    return corrupt_reads(
        cas_register_history(N_OPS, concurrency=8, crash_p=0.0005, seed=4),
        n=2, seed=4, within=0.15)


def build_ablation():
    # Concludes (valid) with ghost subsumption at O(crashes) configurations;
    # without it (JTPU_SUBSUME=0) the same history needs ~2^12 configs.
    # Writes here REUSE values from the work history's domain, so configs
    # with the same final value but different linearized-ghost subsets are
    # exactly the subsumption-collapsible family.
    from jepsen_tpu.history import History
    from jepsen_tpu.synth import cas_register_history, ghost_write_burst
    k = 4 if SMOKE else 12
    burst = ghost_write_burst(k, base_value=0)
    for i, op in enumerate(burst):  # fold values into the tiny work domain
        if op.value is not None:
            burst[i] = op.with_(value=op.value % 3)
    return History(
        burst + list(cas_register_history(800, concurrency=4, crash_p=0.0,
                                          seed=5)),
        reindex=True)


def build_batch():
    from jepsen_tpu.synth import cas_register_history, corrupt_reads
    hs = [cas_register_history(BATCH_OPS, concurrency=6, crash_p=0.005,
                               seed=100 + i) for i in range(BATCH_N)]
    for i in range(0, BATCH_N, 4):  # quarter refuted: mixed verdict stream
        hs[i] = corrupt_reads(hs[i], n=1, seed=i)
    return hs


# ---------------------------------------------------------------------------
# Warm-up: AOT-compile exactly the engine shapes a tier's run can reach
# ---------------------------------------------------------------------------


def warm_shapes(model, window, caps, gw, chunk=512):
    """Compile every (window, capacity, gwords, chunk) engine an escalating
    check() on this tier could request, by running each on one all-NOP
    chunk of the size the driver will really dispatch (capacity-invariant
    — wgl_tpu.chunk_for_capacity returns the base chunk).  NOP
    events take the identity branch of the event switch — no closure, no
    search — so unlike round 2's run-a-real-history warm-up this cannot
    blow up on the history itself, and the call path leaves the jit
    dispatch cache hot for the timed runs."""
    import jax
    import jax.numpy as jnp
    from jepsen_tpu.checker import wgl_tpu
    for cap in caps:
        cc = wgl_tpu.chunk_for_capacity(cap, chunk)
        ev = jnp.full((cc, 10), 0, jnp.int32).at[:, 0].set(wgl_tpu.EV_NOP)
        carry0, run_chunk = wgl_tpu._get_run_chunk(model, window, cap, gw)
        carry, flags = run_chunk(carry0(), ev)
        jax.block_until_ready(flags)


def cap_ladder(start, max_cap, growth=4):
    caps = [start]
    while caps[-1] < max_cap:
        caps.append(min(caps[-1] * growth, max_cap))
    return caps


# ---------------------------------------------------------------------------
# Tier workers (each runs in its own subprocess)
# ---------------------------------------------------------------------------


def tier_cpu():
    """Measure the CPU oracle with a hard timeout — this is the 'CPU
    knossos' baseline the device tier is claimed against.  ``hard`` is the
    SAME history the device hard tier runs (round-4 review: the ~12x
    device advantage on the crash-heavy shape needs a committed CPU
    number, not a stale README claim)."""
    from jepsen_tpu.checker import wgl_cpu
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.synth import cas_register_history
    model = CASRegister()
    out = {}
    hs = {
        "200": cas_register_history(200, concurrency=8, crash_p=0.003,
                                    seed=1),
        "1k": cas_register_history(1000, concurrency=8, crash_p=0.001,
                                   seed=2),
        "10k": build_easy(),
        "hard": build_hard(),
    }
    for name, h in hs.items():
        progress(f"cpu {name}")
        cancel = threading.Event()
        timer = threading.Timer(CPU_TIMEOUT_S, cancel.set)
        timer.start()
        t0 = time.time()
        try:
            r = wgl_cpu.check(model, h, cancel=cancel)
            out[name] = {"wall_s": round(time.time() - t0, 3),
                         "valid": r["valid"],
                         "configs_explored": r.get("configs-explored")}
        except wgl_cpu.Cancelled:
            out[name] = {"wall_s": round(time.time() - t0, 3),
                         "timeout": True, "timeout_s": CPU_TIMEOUT_S}
        except wgl_cpu.SearchExploded as e:
            out[name] = {"wall_s": round(time.time() - t0, 3),
                         "exploded_at": e.n}
        finally:
            timer.cancel()
    emit(out)


def _device_tier(history, *, capacity, max_capacity, runs, explain=True,
                 model_name="cas-register", model_kw=None,
                 fission_threshold=None):
    """``fission_threshold`` routes the timed runs through
    ``engine.fission.check`` (monolithic ladder clamped to the threshold,
    frontier fission above it) instead of the bare wgl_tpu ladder.  Only
    the rungs UP TO the threshold are warmed; sub-problem dispatches
    compile their own small bucket shapes, absorbed by the shakeout."""
    from jepsen_tpu.checker import wgl_tpu
    from jepsen_tpu.checker.prep import prepare
    from jepsen_tpu.models import get_model
    model = get_model(model_name, **(model_kw or {}))
    prep = prepare(history, model)
    window = wgl_tpu._round_window(prep.window)
    gw = wgl_tpu.chosen_gwords(prep)
    cc = wgl_tpu.auto_chunk(prep, model)
    warm_cap = (max_capacity if fission_threshold is None
                else min(max_capacity, fission_threshold))
    if fission_threshold is None:
        def run_check(explain=explain):
            return wgl_tpu.check(model, history, prepared=prep,
                                 capacity=capacity, chunk=cc,
                                 max_capacity=max_capacity, explain=explain)
    else:
        from jepsen_tpu.engine import fission

        def run_check(explain=explain):
            return fission.check(model, history, prepared=prep,
                                 capacity=capacity, chunk=cc,
                                 max_capacity=max_capacity,
                                 threshold=fission_threshold,
                                 explain=explain)
    progress(f"warm window={window} gw={gw} chunk={cc} "
             f"caps={cap_ladder(capacity, warm_cap)}")
    t0 = time.time()
    warm_shapes(model, window, cap_ladder(capacity, warm_cap), gw,
                chunk=cc)
    warm_s = round(time.time() - t0, 1)
    # One untimed SHAKEOUT run: warm_shapes covers the engine programs,
    # but the first real check also touches the event-stream slicer (jit
    # retraces per stream shape), the grow/shrink escalation paths, and —
    # right after a compile-heavy tier — a possibly still-congested
    # tunneled compile service (BENCH_r04's refuted tier measured a
    # 14.5 s first run vs 0.59 s steady; standalone cold-cache the same
    # first run is 1.0 s).  The shakeout absorbs all of that outside the
    # timed region and is disclosed in the artifact.
    t0 = time.time()
    run_check(explain=False)
    shakeout_s = round(time.time() - t0, 2)
    progress(f"timed runs (shakeout {shakeout_s}s)")
    r, walls = timed_runs(run_check, runs)
    return r, walls, {"window": prep.window, "gwords": gw, "chunk": cc,
                      "warm_s": warm_s, "shakeout_s": shakeout_s}


def tier_easy():
    easy_cap = 4096 if SMOKE else 16384
    r, walls, meta = _device_tier(build_easy(), capacity=1024,
                                  max_capacity=easy_cap, runs=3)
    assert r["valid"] is True, r
    emit({"runs": walls, "valid": r["valid"],
          "configs_explored": r.get("configs-explored"),
          "max_capacity_reached": r.get("max-capacity-reached"), **meta})


def tier_hard():
    # Two timed runs: the delta closure brought this tier from ~119 s
    # (round 3) to ~38 s, so a second sample is affordable — closing the
    # round-3 review's "the tier that carries the TPU-advantage story has
    # a single sample" gap.  Compiles are excluded via warm_shapes.
    hard_cap = 4096 if SMOKE else 65536
    r, walls, meta = _device_tier(build_hard(), capacity=1024,
                                  max_capacity=hard_cap, runs=2)
    emit({"runs": walls, "valid": r["valid"],
          "configs_explored": r.get("configs-explored"),
          "max_capacity_reached": r.get("max-capacity-reached"),
          "error": r.get("error"), **meta})


def tier_ceiling():
    # The 2^18-state burst cannot conclude below the 65536 ceiling (it
    # exceeds it 4x).  Through round 5 the claim under test was *bounded
    # degradation*: escalate the whole documented ladder and conclude
    # "unknown" inside a wall budget.  With frontier fission
    # (engine.fission) the same shape must now return a REAL verdict: the
    # threshold-clamped ladder overflows, the search splits into
    # independent per-element components (P-compositionality), the
    # sub-problems run as small cache-hot batch/megabatch lanes, and the
    # recombination is valid True — `max_capacity_reached` stops being
    # this tier's failure mode.  The smoke run forces the split under the
    # tiny CPU-backend cap with an explicitly small threshold.
    from jepsen_tpu.engine import fission
    hard_cap = 4096 if SMOKE else 65536
    verdict_budget_s = 300.0 if SMOKE else 900.0
    thr = 64 if SMOKE else fission.DEFAULT_THRESHOLD
    fission.reset_fission_stats()
    r, walls, meta = _device_tier(build_ceiling(), capacity=1024,
                                  max_capacity=hard_cap, runs=1,
                                  model_name="bitset-256",
                                  fission_threshold=thr)
    assert r["valid"] is True, r  # a real verdict, not max_capacity_reached
    assert walls[0] < verdict_budget_s, (walls, verdict_budget_s)
    emit({"runs": walls, "valid": r["valid"],
          "configs_explored": r.get("configs-explored"),
          "fission": r.get("fission"),
          "fission_threshold": thr,
          "fission_stats": fission.fission_stats(),
          "real_verdict_timed": walls[0] < verdict_budget_s,
          "verdict_budget_s": verdict_budget_s,
          "error": r.get("error"), **meta})


def tier_refuted():
    h = build_refuted()
    r, walls, meta = _device_tier(h, capacity=1024,
                                  max_capacity=4096 if SMOKE else 16384,
                                  runs=2, explain=False)
    assert r["valid"] is False, r
    # Early exit: the corrupted read sits in the first 15% of the history
    # (build_refuted), so the chunk-boundary failure poll must have stopped
    # dispatch inside the first 20% of the stream.
    frac = r["op"]["index"] / len(h.ops)
    assert frac < 0.20, (r["op"]["index"], len(h.ops))
    emit({"runs": walls, "failed_op_index": r["op"]["index"],
          "stream_fraction_to_refute": round(frac, 4),
          "configs_explored": r.get("configs-explored"), **meta})


def tier_ablation():
    """Run under JTPU_SUBSUME=1 (orchestrator tier ablation_on) and =0
    (ablation_off); the off-run measures the classic 2^crashes regime the
    subsumption claim is about."""
    from jepsen_tpu.ops import dedup
    max_cap = 4096 if SMOKE else 65536
    r, walls, meta = _device_tier(build_ablation(), capacity=256,
                                  max_capacity=max_cap, runs=2)
    emit({"runs": walls, "valid": r["valid"], "subsume": dedup.SUBSUME,
          "configs_explored": r.get("configs-explored"),
          "max_capacity_reached": r.get("max-capacity-reached"),
          "error": r.get("error"), **meta})


def build_batch512():
    from jepsen_tpu.synth import cas_register_history, corrupt_reads
    n = 64 if SMOKE else 512
    hs = [cas_register_history(BATCH_OPS, concurrency=6, crash_p=0.005,
                               seed=500 + i) for i in range(n)]
    for i in range(0, n, 4):
        hs[i] = corrupt_reads(hs[i], n=1, seed=i)
    return hs


def tier_batch():
    """Batch offload throughput + the honest same-host CPU comparison the
    round-4 review asked for: histories/sec BOTH ways, per CPU core and
    per socket (this bench host's socket, os.cpu_count() cores), plus the
    break-even core count.  Two shapes: the legacy 96-lane stream
    (round-over-round comparability) and the 512-lane group that is the
    measured throughput knee (parallel/batch.py MAX_LANES_PER_GROUP).
    Since round 6 the timed path is the megabatch pipeline
    (parallel/megabatch.py) — continuous lane refill, O(1) per-dispatch
    readback — parity-checked lane for lane against the CPU oracle on
    the sampled lanes."""
    from jepsen_tpu.checker import wgl_cpu
    from jepsen_tpu.models import CASRegister, get_model
    from jepsen_tpu.parallel.megabatch import check_megabatch
    model = get_model("cas-register")
    out = {}
    for name, hs in (("96", build_batch()), ("512", build_batch512())):
        progress(f"batch[{name}] warm (jit keys on the batch dim)")
        check_megabatch(model, hs)
        progress(f"batch[{name}] timed run")
        t0 = time.time()
        res = check_megabatch(model, hs)
        wall = time.time() - t0
        n_false = sum(1 for r in res if r["valid"] is False)
        assert n_false == len(hs) // 4, [r["valid"] for r in res]
        # CPU oracle on a sample of the same lanes, single core — and the
        # lane-for-lane verdict parity check on that sample.
        sample = hs[:16]
        t0 = time.time()
        for h, r in zip(sample, res):
            assert wgl_cpu.check(CASRegister(), h)["valid"] == r["valid"]
        per = (time.time() - t0) / len(sample)
        cores = os.cpu_count() or 1
        dev_hps = len(hs) / wall
        cpu_core = 1.0 / per
        out[name] = {
            "n_histories": len(hs), "ops_each": BATCH_OPS,
            "wall_s": round(wall, 3),
            "histories_per_sec": round(dev_hps, 1),
            "cpu_s_per_history_1core": round(per, 4),
            "cpu_histories_per_sec_core": round(cpu_core, 1),
            "host_cores": cores,
            "cpu_histories_per_sec_socket": round(cores * cpu_core, 1),
            "device_vs_socket": round(dev_hps / (cores * cpu_core), 2),
            "break_even_cores": round(dev_hps / cpu_core, 1),
        }
    emit({**out["96"], "shapes": out, "analyzer": "wgl-tpu-megabatch"})


def tier_batch_sweep():
    """Throughput trajectory of the megabatch path vs batch size — the
    histories/sec curve at 96/512/2048/8192 lanes (smoke: shrunk), same
    per-lane workload as the batch tier.  Tracked in the bench JSON like
    the headline so the batch-throughput race is measured round over
    round, not anecdotally."""
    from jepsen_tpu.models import get_model
    from jepsen_tpu.parallel.megabatch import (check_megabatch,
                                               megabatch_stats,
                                               reset_megabatch_stats)
    from jepsen_tpu.synth import cas_register_history, corrupt_reads
    model = get_model("cas-register")
    sizes = (16, 32, 64) if SMOKE else (96, 512, 2048, 8192)
    n_max = max(sizes)
    hs = [cas_register_history(BATCH_OPS, concurrency=6, crash_p=0.005,
                               seed=500 + i) for i in range(n_max)]
    for i in range(0, n_max, 4):
        hs[i] = corrupt_reads(hs[i], n=1, seed=i)
    progress("batch_sweep warm")
    check_megabatch(model, hs[:sizes[0]])
    sweep = {}
    for n in sizes:
        progress(f"batch_sweep[{n}] timed run")
        reset_megabatch_stats()
        t0 = time.time()
        res = check_megabatch(model, hs[:n])
        wall = time.time() - t0
        n_false = sum(1 for r in res if r["valid"] is False)
        assert n_false == n // 4, n_false
        st = megabatch_stats()
        sweep[str(n)] = {
            "n_histories": n, "ops_each": BATCH_OPS,
            "wall_s": round(wall, 3),
            "histories_per_sec": round(n / wall, 1),
            "dispatches": st["dispatches"], "refills": st["refills"],
            "groups": st["groups"],
        }

    # The plugin-model lanes through the same sweep: queue/set/opacity
    # hist/s on the megabatch path vs the check_batch barrier, parity-
    # asserted lane for lane (the state-width ladder's before/after).
    from jepsen_tpu.parallel.batch import check_batch
    models_out = {}
    for name, m, runs, wf, evf in resolve_model_runs():
        progress(f"batch_sweep[models:{name}] warm")
        check_batch(m, runs, window_floor=wf, capacity=256)
        check_megabatch(m, runs, window_floor=wf, ev_floor=evf,
                        capacity=256)
        progress(f"batch_sweep[models:{name}] timed runs")
        t0 = time.time()
        mres = check_megabatch(m, runs, window_floor=wf, ev_floor=evf,
                               capacity=256)
        mega_wall = time.time() - t0
        t0 = time.time()
        bres = check_batch(m, runs, window_floor=wf, capacity=256)
        batch_wall = time.time() - t0
        assert [r["valid"] for r in mres] == [r["valid"] for r in bres]
        models_out[name] = {
            "n_histories": len(runs),
            "megabatch_hist_per_sec": round(len(runs) / mega_wall, 1),
            "check_batch_hist_per_sec": round(len(runs) / batch_wall, 1),
            "parity": "lane-for-lane valid vs check_batch",
        }
    emit({"sweep": sweep, "models": models_out,
          "analyzer": "wgl-tpu-megabatch",
          "histories_per_sec":
              sweep[str(sizes[-1])]["histories_per_sec"]})


def build_multireg():
    from jepsen_tpu.synth import multi_register_history
    return multi_register_history(N_OPS, keys=3, concurrency=8,
                                  crash_p=0.0005, seed=77)


def tier_multireg():
    """Multi-key register history (BASELINE configs #4/#5: the
    cockroach/tidb/yugabyte multi-key shapes) on the round-5 device-tier
    MultiRegister (k int32 lanes) vs the host oracle on the same
    history."""
    from jepsen_tpu.checker import wgl_cpu
    from jepsen_tpu.models import MultiRegister, get_model
    h = build_multireg()
    r, walls, meta = _device_tier(
        h, capacity=1024, max_capacity=4096 if SMOKE else 16384, runs=2,
        model_name="multi-register", model_kw={"keys": 3, "vbits": 3})
    assert r["valid"] is True, r
    cancel = threading.Event()
    timer = threading.Timer(CPU_TIMEOUT_S, cancel.set)
    timer.start()
    t0 = time.time()
    try:
        c = wgl_cpu.check(MultiRegister(), h, cancel=cancel)
        cpu = {"wall_s": round(time.time() - t0, 3), "valid": c["valid"]}
    except wgl_cpu.Cancelled:
        cpu = {"wall_s": round(time.time() - t0, 3), "timeout": True}
    finally:
        timer.cancel()
    import statistics as st
    dev = st.median(walls)
    # Fission guard-rail: this tier's 16384 cap sits AT the default
    # fission threshold, so engine.fission.check takes the plain
    # monolithic path here — the wall time must not move vs the
    # BENCH_r05 baseline (35.9 s/run, non-smoke device runs only; the
    # delta is reported, the orchestrator budget enforces the bound).
    r05_s = 35.9
    emit({"runs": walls, "valid": r["valid"],
          "configs_explored": r.get("configs-explored"),
          "max_capacity_reached": r.get("max-capacity-reached"),
          "r05_baseline_s_per_run": r05_s,
          "delta_vs_r05_s": (None if SMOKE else round(dev - r05_s, 3)),
          "cpu": cpu,
          # On CPU timeout the ratio is a LOWER bound (flagged).
          "vs_cpu": (round(cpu["wall_s"] / dev, 2)
                     if cpu.get("wall_s") else None),
          "vs_cpu_is_lower_bound": bool(cpu.get("timeout")),
          **meta})


def build_elle():
    from jepsen_tpu.synth import list_append_history
    n = 16 if SMOKE else 96
    # Every 4th lane corrupted: the batch exercises both the acyclic fast
    # path (device flags only, no CPU search) and the cyclic witness path.
    return [list_append_history(n_txns=100, keys=4, concurrency=6,
                                seed=3000 + i,
                                anomaly_p=0.3 if i % 4 == 0 else 0.0)
            for i in range(n)]


def tier_elle():
    """Transactional-anomaly engine (elle_tpu) throughput on the acceptance
    shape — a 96-history x 200-op list-append batch — with the same honest
    same-host CPU comparison as tier_batch: histories/sec both ways, per
    core and per socket, and the break-even core count.  Every lane is
    parity-checked against the CPU elle oracle (verdict + anomaly set)
    before any number is emitted."""
    from jepsen_tpu import elle_tpu
    from jepsen_tpu.elle import list_append
    hs = build_elle()
    progress(f"elle warm ({len(hs)} lanes, closure kernel compile)")
    elle_tpu.check_batch(hs, workload="list-append")
    progress("elle timed device run")
    t0 = time.time()
    res = elle_tpu.check_batch(hs, workload="list-append")
    wall = time.time() - t0
    progress("elle CPU oracle pass (full batch, timed)")
    t0 = time.time()
    cpu_res = [list_append.check(h) for h in hs]
    cpu_wall = time.time() - t0
    for i, (d, c) in enumerate(zip(res, cpu_res)):
        assert d["valid"] == c["valid"] and \
            d.get("anomaly-types", []) == c.get("anomaly-types", []), \
            (i, d.get("anomaly-types"), c.get("anomaly-types"))
    n_false = sum(1 for r in res if r["valid"] is False)
    cores = os.cpu_count() or 1
    dev_hps = len(hs) / wall
    cpu_core = len(hs) / cpu_wall
    emit({
        "n_histories": len(hs), "ops_each": 200,
        "n_refuted": n_false,
        "parity": "all-lanes verdict+anomaly-set vs CPU oracle",
        "analyzer": res[0].get("analyzer"),
        "wall_s": round(wall, 3),
        "histories_per_sec": round(dev_hps, 1),
        "cpu_wall_s": round(cpu_wall, 3),
        "cpu_histories_per_sec_core": round(cpu_core, 1),
        "host_cores": cores,
        "cpu_histories_per_sec_socket": round(cores * cpu_core, 1),
        "device_vs_socket": round(dev_hps / (cores * cpu_core), 2),
        "break_even_cores": round(dev_hps / cpu_core, 1),
    })


def build_model_batches():
    # Queue histories keep concurrency 2: the ring-buffer state is wide
    # (2 + slots int32 lanes), so the per-capacity sort network is the
    # compile hog AND the frontier grows fast with overlap — conc 2 keeps
    # the smoke run inside one compile at capacity 256.  Set/txn states
    # are 2-3 ints; they afford real overlap.
    from jepsen_tpu.synth import queue_history, set_history, txn_history
    n = 8 if SMOKE else 64
    n_ops = 24 if SMOKE else 48
    return {
        "fifo-queue": [queue_history(n_ops=n_ops, concurrency=2, seed=s)
                       for s in range(n)],
        "set": [set_history(n_ops=n_ops, concurrency=2 if SMOKE else 4,
                            seed=s) for s in range(n)],
        "opacity": [txn_history(n_txns=max(12, n_ops // 2),
                                concurrency=2 if SMOKE else 4,
                                seed=s) for s in range(n)],
    }


def resolve_model_runs():
    """(name, model, runs, window_floor, ev_floor) per plugin-model
    family, with the same sizing the serve path derives: queue slots off
    ``derive_queue_slots``, opacity through its reduction, floors off
    the pow2 ladder.  Shared by the models tier and the batch_sweep
    plugin sub-sweep so both measure the same resolved workloads."""
    from jepsen_tpu.engine.model_plugin import derive_queue_slots
    from jepsen_tpu.engine.opacity import derive_history
    from jepsen_tpu.models import get_model
    from jepsen_tpu.serve.buckets import (MIN_EVENTS_BUCKET,
                                          MIN_WIDTH_BUCKET, pow2_at_least)
    out = []
    for name, hs in build_model_batches().items():
        if name == "opacity":
            model = get_model("txn-register")
            runs = [derive_history(h) for h in hs]
        elif name == "fifo-queue":
            slots = max(derive_queue_slots(h, {})["slots"] for h in hs)
            model = get_model(name, slots=slots)
            runs = hs
        else:
            model = get_model(name)
            runs = hs
        width = max(len({o.process for o in h.client_ops()})
                    for h in runs)
        wf = pow2_at_least(width, MIN_WIDTH_BUCKET)
        evf = pow2_at_least(max(len(h) for h in runs), MIN_EVENTS_BUCKET)
        out.append((name, model, runs, wf, evf))
    return out


def tier_models():
    """Engine-plugin model throughput: hist/s for each of the three
    drop-in models (fifo-queue, set, opacity via its reduction onto
    txn-register) through the batch engine — the line the engine-smoke
    CI job tracks.  Every lane is parity-checked against the host oracle
    before any number is emitted.  Each model also reports its
    steady-state ``compiles_per_1k_dispatches`` through a warm megabatch
    pass (the /metrics gauge, measured here: a warm ladder reads 0.0)."""
    from jepsen_tpu.checker import wgl_cpu
    from jepsen_tpu.obs.hist import compile_event_count
    from jepsen_tpu.parallel.batch import check_batch
    from jepsen_tpu.parallel.megabatch import (check_megabatch,
                                               megabatch_stats)

    out = {}
    for name, model, runs, floor, evf in resolve_model_runs():
        progress(f"models[{name}] warm ({len(runs)} lanes)")
        check_batch(model, runs, window_floor=floor, capacity=256)
        progress(f"models[{name}] timed device run")
        t0 = time.time()
        res = check_batch(model, runs, window_floor=floor, capacity=256)
        wall = time.time() - t0
        for i, (r, h) in enumerate(zip(res, runs)):
            c = wgl_cpu.check(model.cpu_model(), h)
            assert r["valid"] == c["valid"], (name, i, r, c)
        # Steady-state compile pressure on the megabatch path: warm the
        # ladder with one pass, then count compile events per 1k chunk
        # dispatches over an identical second pass.
        mres = check_megabatch(model, runs, window_floor=floor,
                               ev_floor=evf, capacity=256)
        assert [r["valid"] for r in mres] == [r["valid"] for r in res]
        c0, d0 = compile_event_count(), megabatch_stats()["dispatches"]
        check_megabatch(model, runs, window_floor=floor, ev_floor=evf,
                        capacity=256)
        dd = megabatch_stats()["dispatches"] - d0
        dc = compile_event_count() - c0
        out[name] = {
            "n_histories": len(runs),
            "wall_s": round(wall, 3),
            "histories_per_sec": round(len(runs) / wall, 1),
            "parity": "all-lanes verdict vs CPU oracle",
            "compiles_per_1k_dispatches":
                round(1000.0 * dc / max(1, dd), 3),
        }
    emit({"models": out})


def tier_sched():
    """Generator scheduler throughput — the committed record behind the
    ~24k ops/s claim (round-4 review: the number lived only in a test
    docstring; reference bar: generator.clj:67-70 cites >20k/s).  Two
    shapes: the pure mix through the simulator (completion/update costs
    included) and the realistic wrapped stack (clients + time_limit)."""
    from jepsen_tpu import generator as gen
    from jepsen_tpu.generator import testkit
    n = 5_000 if SMOKE else 20_000
    out = {}
    best = 0.0
    for _ in range(3):
        g = gen.limit(n, gen.mix([gen.repeat({"f": "r"}),
                                  gen.repeat({"f": "w", "value": 1})]))
        t0 = time.time()
        h = testkit.quick(g, concurrency=10, complete_fn=testkit.instant)
        dt = time.time() - t0
        assert sum(1 for o in h if o.type == "invoke") == n
        best = max(best, n / dt)
    out["pure_mix_ops_per_sec"] = round(best, 0)
    best = 0.0
    for _ in range(3):
        g = gen.time_limit(3600, gen.clients(gen.limit(
            n, gen.mix([gen.repeat({"f": "r"}),
                        gen.repeat({"f": "w", "value": 1})]))))
        t0 = time.time()
        h = testkit.quick(g, concurrency=10, complete_fn=testkit.instant)
        dt = time.time() - t0
        best = max(best, n / dt)
    out["wrapped_stack_ops_per_sec"] = round(best, 0)
    out["reference_bar_ops_per_sec"] = 20_000
    # Best-of-3, NOT the bench's usual post-shakeout median: scheduler
    # throughput is a pure-host figure whose low outliers are scheduler
    # noise (GC, the suite running alongside), and the reference's cited
    # figure (generator.clj:67-70) is likewise a best-case rate.
    out["timing"] = "best-of-3"
    emit(out)


def tier_setup2():
    """Fresh-process cold-start: with the persistent compilation cache this
    is a disk load, not a recompile."""
    t0 = time.time()
    from jepsen_tpu.checker import wgl_tpu
    from jepsen_tpu.models import get_model
    from jepsen_tpu.synth import cas_register_history
    m = get_model("cas-register")
    h = cas_register_history(200, concurrency=8, crash_p=0.005, seed=7)
    r = wgl_tpu.check(m, h, capacity=1024)
    assert r["valid"] is True
    emit({"setup_s": round(time.time() - t0, 1)})


def tier_fleet():
    """Fleet serving tier: the routed 3-worker fleet vs one CheckService
    on the same workload (the price of fault tolerance on a healthy
    fleet), plus the recovery wall when a worker is killed mid-campaign
    (the bound the chaos smoke asserts against the deadline budget)."""
    from jepsen_tpu.serve import CheckService
    from jepsen_tpu.serve.fleet import Fleet
    from jepsen_tpu.synth import cas_register_history
    n = 24 if SMOKE else 96
    hists = [cas_register_history(60, concurrency=4, seed=s)
             for s in range(n)]

    def run(svc):
        t0 = time.time()
        reqs = [svc.submit(h, kind="wgl", model="cas-register",
                           deadline_s=120.0) for h in hists]
        vals = [r.wait(timeout=300)["valid"] for r in reqs]
        return time.time() - t0, vals

    solo = CheckService(max_lanes=32, capacity=64)
    run(solo)                                   # warm the bucket ladder
    t_solo, v_solo = run(solo)
    solo.close(timeout=60.0)

    fleet = Fleet(workers=3, max_lanes=32, capacity=64,
                  default_deadline_s=120.0)
    run(fleet)
    t_fleet, v_fleet = run(fleet)
    assert v_fleet == v_solo, "fleet verdicts diverge from solo service"

    # Recovery wall: kill a worker with the campaign in flight; every
    # cell must still complete (rerouted/hedged to the siblings).
    reqs = [fleet.submit(h, kind="wgl", model="cas-register",
                         deadline_s=120.0) for h in hists]
    t0 = time.time()
    fleet.workers[0].kill()
    v_kill = [r.wait(timeout=300)["valid"] for r in reqs]
    recovery_s = time.time() - t0
    fleet.restart_worker(0)
    snap = fleet.metrics.snapshot()
    fleet.close(timeout=60.0)
    assert v_kill == v_solo, "verdicts diverged under worker kill"
    emit({"n_histories": n,
          "solo_s": round(t_solo, 3),
          "fleet_s": round(t_fleet, 3),
          "fleet_overhead": round(t_fleet / t_solo, 2) if t_solo else None,
          "kill_recovery_s": round(recovery_s, 3),
          "rerouted": snap["counters"].get("cells-rerouted", 0),
          "hedges": snap["counters"].get("hedges", 0),
          "worker_failures": snap["counters"].get("worker-failures", 0)})


def tier_procfleet():
    """Out-of-process fleet tier: real worker subprocesses behind the
    wire protocol + net_proxy links vs one in-process CheckService — the
    price of the process boundary and the socket hop on a healthy fleet
    — plus the recovery wall when a worker PROCESS is SIGKILLed
    mid-campaign (supervisor respawn + reroute, the bound the procfleet
    chaos smoke asserts against the deadline budget)."""
    from jepsen_tpu.serve import CheckService
    from jepsen_tpu.serve.chaos import ChaosNemesis
    from jepsen_tpu.serve.fleet import ProcFleet
    from jepsen_tpu.synth import cas_register_history
    n = 16 if SMOKE else 64
    hists = [cas_register_history(60, concurrency=4, seed=s)
             for s in range(n)]

    def run(svc):
        t0 = time.time()
        reqs = [svc.submit(h, kind="wgl", model="cas-register",
                           deadline_s=120.0) for h in hists]
        vals = [r.wait(timeout=300)["valid"] for r in reqs]
        return time.time() - t0, vals

    solo = CheckService(max_lanes=32, capacity=64)
    run(solo)                                   # warm the bucket ladder
    t_solo, v_solo = run(solo)
    solo.close(timeout=60.0)

    fleet = ProcFleet(workers=3, spawn=True, max_lanes=32, capacity=64,
                      default_deadline_s=120.0)
    run(fleet)                                  # warm the worker procs
    t_fleet, v_fleet = run(fleet)
    assert v_fleet == v_solo, "procfleet verdicts diverge from solo"

    # Partition wall: sever one worker's wire mid-campaign, heal it.
    chaos = ChaosNemesis(fleet)
    reqs = [fleet.submit(h, kind="wgl", model="cas-register",
                         deadline_s=120.0) for h in hists]
    t0 = time.time()
    key = chaos.partition_worker(0)
    v_part = [r.wait(timeout=300)["valid"] for r in reqs]
    partition_s = time.time() - t0
    chaos.heal(key)
    assert v_part == v_solo, "verdicts diverged under partition"

    # Recovery wall: SIGKILL a worker process with the campaign in
    # flight; the supervisor respawns it, the drivers reroute.
    reqs = [fleet.submit(h, kind="wgl", model="cas-register",
                         deadline_s=120.0) for h in hists]
    t0 = time.time()
    fleet.workers[1].kill()
    v_kill = [r.wait(timeout=300)["valid"] for r in reqs]
    recovery_s = time.time() - t0
    snap = fleet.metrics.snapshot()
    fleet.close(timeout=60.0)
    assert v_kill == v_solo, "verdicts diverged under process kill"
    emit({"n_histories": n,
          "solo_s": round(t_solo, 3),
          "procfleet_s": round(t_fleet, 3),
          "wire_overhead": round(t_fleet / t_solo, 2) if t_solo else None,
          "partition_recovery_s": round(partition_s, 3),
          "kill_recovery_s": round(recovery_s, 3),
          "rerouted": snap["counters"].get("cells-rerouted", 0),
          "hedges": snap["counters"].get("hedges", 0),
          "respawns": snap["counters"].get("supervisor-respawns", 0),
          "worker_failures": snap["counters"].get("worker-failures", 0)})


def tier_obs():
    """Observability tier: what the flight recorder costs on a hot
    serving path.  The same warmed campaign runs with the recorder off,
    then on — the ratio is the toll the ISSUE budget caps at 2% — and
    the latency histograms filled along the way must report nonzero
    p50/p99 for the two headline lifecycle edges (enqueue→dispatch,
    dispatch→verdict), or the instrument measured nothing.  Then the
    same off-vs-on shape for the Watchtower telemetry plane: a warmed
    ProcFleet campaign with pushes disabled vs pushing at a fast
    cadence (same <2% budget), and finally a short monitored check so
    the monitor's per-epoch spans provably land in the merged Perfetto
    export next to the serving spans."""
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.monitor import Monitor
    from jepsen_tpu.obs.recorder import RECORDER
    from jepsen_tpu.serve import CheckService
    from jepsen_tpu.serve.fleet import ProcFleet
    from jepsen_tpu.synth import cas_register_history
    n = 24 if SMOKE else 96
    reps = 2 if SMOKE else 3
    hists = [cas_register_history(60, concurrency=4, seed=s)
             for s in range(n)]

    def run(svc):
        t0 = time.time()
        reqs = [svc.submit(h, kind="wgl", model="cas-register",
                           deadline_s=120.0) for h in hists]
        for r in reqs:
            assert r.wait(timeout=300)["valid"] is True
        return time.time() - t0

    svc = CheckService(max_lanes=32, capacity=64)
    run(svc)                                    # warm the bucket ladder
    # min-of-reps on each side: overhead is a systematic cost, the
    # best-case walls are the fairest pair to ratio.
    RECORDER.disable()
    t_off = min(run(svc) for _ in range(reps))
    RECORDER.enable()
    RECORDER.clear()
    t_on = min(run(svc) for _ in range(reps))
    rec = RECORDER.stats()
    snap = svc.metrics.snapshot()
    svc.close(timeout=60.0)

    assert rec["recorded"] > 0, "recorder captured nothing while enabled"
    edges = {}
    for edge in ("edge:enqueue->dispatch", "edge:dispatch->verdict"):
        h = snap["histograms"].get(edge) or {}
        assert (h.get("p50") or 0) > 0 and (h.get("p99") or 0) > 0, \
            f"histogram {edge} is empty/zero: the instrument measured nothing"
        edges[edge] = {"count": h.get("count"),
                       "p50_s": h.get("p50"), "p99_s": h.get("p99")}
    overhead = (t_on / t_off - 1.0) if t_off else None

    # -- Watchtower: what the telemetry push plane costs -------------------
    # Same min-of-reps off-vs-on shape, but through a ProcFleet (the
    # telemetry plane lives in the fleet tier): telemetry_s=0 disables
    # both the worker push loops and the fleet sweep entirely.
    n_tele = 12 if SMOKE else 48
    tele_hists = [cas_register_history(60, concurrency=4, seed=1000 + s)
                  for s in range(n_tele)]

    def fleet_run(fleet):
        t0 = time.time()
        reqs = [fleet.submit(h, kind="wgl", model="cas-register",
                             deadline_s=120.0) for h in tele_hists]
        for r in reqs:
            assert r.wait(timeout=300)["valid"] is True
        return time.time() - t0

    def fleet_wall(telemetry_s):
        fleet = ProcFleet(workers=3, spawn=False, max_lanes=32,
                          capacity=64, default_deadline_s=120.0,
                          telemetry_s=telemetry_s)
        try:
            fleet_run(fleet)                # warm this fleet's lanes
            wall = min(fleet_run(fleet) for _ in range(reps))
            pushes = fleet.telemetry.push_count("fleet")
        finally:
            fleet.close(timeout=60.0)
        return wall, pushes

    t_tele_off, pushes_off = fleet_wall(0.0)
    t_tele_on, pushes_on = fleet_wall(0.25)
    assert pushes_off == 0, "telemetry_s=0 must fully disable the plane"
    assert pushes_on > 0, "telemetry plane pushed nothing while enabled"
    tele_overhead = ((t_tele_on / t_tele_off - 1.0)
                     if t_tele_off else None)

    # -- monitor epoch spans in the merged export --------------------------
    RECORDER.enable()
    mon = Monitor(kind="wgl", model=CASRegister())
    for op in cas_register_history(300, concurrency=4, seed=7):
        mon.offer(op)
    mon.flush()
    mon.close()
    chrome = RECORDER.chrome_events()
    mon_spans = [e for e in chrome
                 if e["cat"] == "monitor" and e.get("ph") == "X"]
    assert mon_spans, ("monitor epoch spans missing from the merged "
                       "Perfetto export")

    emit({"n_histories": n,
          "recorder_off_s": round(t_off, 3),
          "recorder_on_s": round(t_on, 3),
          "recorder_overhead": (round(overhead, 4)
                                if overhead is not None else None),
          "events_recorded": rec["recorded"],
          "events_buffered": rec["buffered"],
          "edges": edges,
          "n_telemetry_histories": n_tele,
          "telemetry_off_s": round(t_tele_off, 3),
          "telemetry_on_s": round(t_tele_on, 3),
          "telemetry_overhead": (round(tele_overhead, 4)
                                 if tele_overhead is not None else None),
          "telemetry_pushes": pushes_on,
          "monitor_epoch_spans": len(mon_spans)})


def tier_elastic():
    """Elastic fleet tier: the Fleetport control plane under membership
    churn.  Workers join (REGISTER over the authenticated wire) and
    leave (lease force-expired by chaos, evicted by the reaper — no
    local signal) while a campaign is in flight; every verdict must
    stay lane-for-lane identical to a solo service.  Join and leave
    walls land in the log-bucketed latency histograms
    (jepsen_tpu.obs.hist) so the tier reports real p50/p99, and the
    flight-recorder toll is re-measured on this topology against the
    same <2% budget tier_obs holds the fixed fleet to."""
    from jepsen_tpu.obs.recorder import RECORDER
    from jepsen_tpu.serve import CheckService
    from jepsen_tpu.serve.chaos import ChaosNemesis
    from jepsen_tpu.serve.fleetport import Fleetport
    from jepsen_tpu.serve.worker_main import FleetRegistration, ThreadWorker
    from jepsen_tpu.synth import cas_register_history
    n = 12 if SMOKE else 48
    reps = 2 if SMOKE else 3
    cycles = 3 if SMOKE else 8
    token = "elastic-bench-token"   # exercised, never emitted
    hists = [cas_register_history(60, concurrency=4, seed=s)
             for s in range(n)]

    solo = CheckService(max_lanes=32, capacity=64)
    reqs = [solo.submit(h, kind="wgl", model="cas-register",
                        deadline_s=120.0) for h in hists]
    v_solo = [r.wait(timeout=300)["valid"] for r in reqs]
    solo.close(timeout=60.0)

    fp = Fleetport(listen_host="127.0.0.1", lease_s=1.0,
                   token=token, max_lanes=32, capacity=64,
                   default_deadline_s=120.0, telemetry_s=0.2)
    live = {}

    def join(name):
        tw = ThreadWorker(name,
                          lambda: CheckService(max_lanes=32, capacity=64),
                          telemetry_s=0.2)
        reg = FleetRegistration(
            tw.server, fleet_addr=("127.0.0.1", fp.listen_port),
            name=name, advertise_host="127.0.0.1", port=tw.server.port,
            token=token)
        t0 = time.time()
        reg.start()
        assert reg.wait_registered(30), f"{name} never registered"
        fp.metrics.hists.observe("fleet:join-s", time.time() - t0)
        live[name] = (tw, reg)

    def leave(name, chaos):
        tw, reg = live.pop(name)
        reg.stop()                      # no comeback after the heal
        key = chaos.expire_lease(name)
        t0 = time.time()
        deadline = t0 + 30
        while time.time() < deadline and fp.registry.is_live(name):
            time.sleep(0.01)
        assert not fp.registry.is_live(name), f"{name} never evicted"
        fp.metrics.hists.observe("fleet:leave-s", time.time() - t0)
        chaos.heal(key)
        tw.terminate()

    def run(svc):
        t0 = time.time()
        rr = [svc.submit(h, kind="wgl", model="cas-register",
                         deadline_s=120.0) for h in hists]
        vals = [r.wait(timeout=300)["valid"] for r in rr]
        return time.time() - t0, vals

    try:
        join("ew0")
        join("ew1")
        run(fp)                         # warm the bucket ladder

        # churn under load: a campaign in flight while a worker joins
        # and another leaves, every cycle
        chaos = ChaosNemesis(fp)
        for c in range(cycles):
            name = f"churn{c}"
            rr = [fp.submit(h, kind="wgl", model="cas-register",
                            deadline_s=120.0) for h in hists]
            join(name)
            leave(name, chaos)
            v = [r.wait(timeout=300)["valid"] for r in rr]
            assert v == v_solo, "verdicts diverged under membership churn"

        # recorder toll on the elastic topology (min-of-reps each side)
        RECORDER.disable()
        t_off = min(run(fp)[0] for _ in range(reps))
        RECORDER.enable()
        RECORDER.clear()
        t_on = min(run(fp)[0] for _ in range(reps))
        _, v_final = run(fp)
        assert v_final == v_solo, "verdicts diverged on elastic fleet"
        snap = fp.metrics.snapshot()
    finally:
        for nm in list(live):
            tw, reg = live.pop(nm)
            reg.stop()
            tw.terminate()
        fp.close(timeout=60.0)

    overhead = (t_on / t_off - 1.0) if t_off else None
    edges = {}
    for edge in ("fleet:join-s", "fleet:leave-s"):
        h = snap["histograms"].get(edge) or {}
        assert (h.get("count") or 0) >= cycles and (h.get("p99") or 0) > 0, \
            f"histogram {edge} is empty: the churn measured nothing"
        edges[edge] = {"count": h.get("count"),
                       "p50_s": h.get("p50"), "p99_s": h.get("p99")}
    emit({"n_histories": n,
          "churn_cycles": cycles,
          "join": edges["fleet:join-s"],
          "leave": edges["fleet:leave-s"],
          "recorder_off_s": round(t_off, 3),
          "recorder_on_s": round(t_on, 3),
          "recorder_overhead": (round(overhead, 4)
                                if overhead is not None else None),
          "evictions": snap["counters"].get("lease-evictions", 0),
          "joins": snap["counters"].get("fleet-joins", 0),
          "rejoins": snap["counters"].get("fleet-rejoins", 0),
          "rerouted": snap["counters"].get("cells-rerouted", 0),
          "auth_rejections": snap["counters"].get("auth-rejections", 0)})


def tier_fleetfission():
    """Hydra tier: giant bitset ceiling histories (2^8-wide frontiers —
    arXiv 2410.04581's undedupable shape) checked three ways: the CPU
    oracle, single-worker window fission at an unpinned ceiling, and the
    3-worker fleet with the per-worker ceiling pinned to 64 configs so
    no lone worker can decide any of them — the verdict only exists
    because the scatter plane fans component projections across the
    fleet and recombines under the unknown-never-false table.  Reports
    the scatter wall against the single-worker wall and the plane
    counters that /metrics exposes."""
    from jepsen_tpu.checker import wgl_cpu, wgl_tpu
    from jepsen_tpu.engine import fission
    from jepsen_tpu.models import get_model
    from jepsen_tpu.serve import fission_plane
    from jepsen_tpu.serve.fleet import Fleet
    from jepsen_tpu.synth import bitset_ceiling_history
    # the orchestrator pins these in the tier subprocess's env before
    # any engine import; a direct --tier run must bring its own pins
    assert os.environ.get("JTPU_FLEETFISSION_THRESHOLD") == "16", \
        "fleetfission tier needs its env pins (run via the orchestrator)"
    n = 4 if SMOKE else 8
    worker_cap = int(os.environ["JTPU_FISSION_THRESHOLD"])
    m = get_model("bitset")
    hists = [bitset_ceiling_history(8, n_clean=3 + (s % 4), concurrency=2)
             for s in range(n)]
    oracle = [wgl_cpu.check(m.cpu_model(), h)["valid"] for h in hists]

    # premise: at the pinned worker ceiling every giant overflows
    progress("fleetfission: proving the per-worker ceiling premise")
    for h in hists:
        r = wgl_tpu.check(m, h, capacity=worker_cap,
                          max_capacity=worker_cap)
        assert r["valid"] == "unknown" and r.get("capacity-exceeded"), \
            "premise broken: a single worker's ceiling decided a giant"

    # single-worker baseline: window fission, ceiling unpinned
    def run_single():
        return [fission.split_check(m, h, capacity=16, max_capacity=65536,
                                    threshold=32)["valid"] for h in hists]

    run_single()                                # warm the engines
    t0 = time.time()
    v_single = run_single()
    t_single = time.time() - t0
    assert v_single == oracle, "single-worker fission diverged from oracle"

    fleet = Fleet(workers=3, max_lanes=16, capacity=worker_cap,
                  hedge_s=5.0, default_deadline_s=240.0)
    try:
        def run_fleet():
            reqs = [fleet.submit(h, kind="wgl", model="bitset",
                                 deadline_s=240.0) for h in hists]
            return [r.wait(timeout=300) for r in reqs]

        progress("fleetfission: warm fleet pass")
        run_fleet()
        t0 = time.time()
        out = run_fleet()
        t_fleet = time.time() - t0
        v_fleet = [r["valid"] for r in out]
        assert v_fleet == oracle, "fleet-scattered verdicts diverged"
        assert all((r.get("fission") or {}).get("distributed")
                   for r in out), "a giant never scattered"
        snap = fleet.metrics.snapshot()
        plane = fission_plane.plane_stats()
    finally:
        fleet.close(timeout=60.0)
    emit({"n_histories": n,
          "events_per_history": [len(h.ops) for h in hists],
          "worker_ceiling": worker_cap,
          "single_s": round(t_single, 3),
          "fleet_s": round(t_fleet, 3),
          "scatter_overhead": (round(t_fleet / t_single, 2)
                               if t_single else None),
          "scattered": plane.get("scattered", 0),
          "remote_subproblems": plane.get("remote-subproblems", 0),
          "cancelled": plane.get("cancelled", 0),
          "witness_recoveries": plane.get("witness-recoveries", 0),
          "hedges": snap["counters"].get("hedges", 0)})


def tier_stream():
    """Pulse tier: one long cas-register stream checked live by the
    device-resident frontier, one epoch at a time, against the cold
    one-shot check of the same history.  The claims under measurement:
    per-epoch wall stays flat from the first post-warmup quarter to the
    last (the frontier extends, never recomputes), steady state makes
    zero recompiles, and the summed stream wall stays within a small
    factor of the single cold check it replaces — the price of getting
    a verdict at every epoch instead of once at the end."""
    from jepsen_tpu.checker import wgl_tpu
    from jepsen_tpu.engine.stream import DeviceKeyFrontier
    from jepsen_tpu.models import CASRegister, get_model
    from jepsen_tpu.obs.hist import compile_event_count
    from jepsen_tpu.synth import cas_register_history
    n_ops = 2_000 if SMOKE else 40_000
    epoch_ops = 256
    jm = get_model("cas-register")
    h = cas_register_history(n_ops, concurrency=4, crash_p=0.0, seed=0)
    ops = list(h)

    def run_stream(record=None):
        f = DeviceKeyFrontier(jm, CASRegister())
        for i in range(0, len(ops), epoch_ops):
            for op in ops[i:i + epoch_ops]:
                f.feed(op)
            t0 = time.time()
            f.advance()
            if record is not None:
                record.append(time.time() - t0)
        f.finalize()
        assert f.verdict()["valid"] is True, "stream tier history refuted"
        assert f.fallback_reason is None, f.fallback_reason
        return f

    progress("stream: warm pass (compiles the epoch-bucket ladder)")
    run_stream()
    warm_compiles = compile_event_count()

    progress("stream: measured pass")
    walls: list = []
    t0 = time.time()
    f = run_stream(record=walls)
    stream_s = time.time() - t0
    recompiles = compile_event_count() - warm_compiles

    progress("stream: cold one-shot baseline")
    wgl_tpu.check(jm, h)                        # warm the one-shot engine
    t0 = time.time()
    cold = wgl_tpu.check(jm, h)
    cold_s = time.time() - t0
    assert cold["valid"] is True

    q = max(1, len(walls) // 4)
    early = statistics.median(walls[1:1 + q])   # skip the first epoch
    late = statistics.median(walls[-q:])
    emit({"n_ops": n_ops, "epoch_ops": epoch_ops,
          "epochs": len(walls),
          "epoch_dispatches": f.epoch_dispatches,
          "steady_recompiles": recompiles,
          "stream_s": round(stream_s, 3),
          "cold_oneshot_s": round(cold_s, 3),
          "stream_over_cold": (round(stream_s / cold_s, 2)
                               if cold_s else None),
          "epoch_wall_early_s": round(early, 4),
          "epoch_wall_late_s": round(late, 4),
          "late_over_early": round(late / early, 2) if early else None})


TIER_FNS = {
    "cpu": tier_cpu,
    "easy": tier_easy,
    "hard": tier_hard,
    "ceiling": tier_ceiling,
    "refuted": tier_refuted,
    "batch": tier_batch,
    "batch_sweep": tier_batch_sweep,
    "ablation_on": tier_ablation,
    "ablation_off": tier_ablation,
    "setup2": tier_setup2,
    "sched": tier_sched,
    "multireg": tier_multireg,
    "elle": tier_elle,
    "models": tier_models,
    "fleet": tier_fleet,
    "procfleet": tier_procfleet,
    "obs": tier_obs,
    "elastic": tier_elastic,
    "fleetfission": tier_fleetfission,
    "stream": tier_stream,
}


# ---------------------------------------------------------------------------
# Orchestrator
# ---------------------------------------------------------------------------


def run_tier(name: str) -> dict:
    """Run one tier in a subprocess; never raises.  Returns
    {"status": ok|crashed|timeout, "wall_s", ...data or stderr tail}."""
    env = dict(os.environ)
    if name == "ablation_on":
        env["JTPU_SUBSUME"] = "1"
    elif name == "ablation_off":
        env["JTPU_SUBSUME"] = "0"
    elif name == "fleetfission":
        # pinned BEFORE the tier subprocess imports any engine: every
        # worker's WGL ceiling is 64 configs, scatter threshold 16 events
        env["JTPU_FISSION_THRESHOLD"] = "64"
        env["JTPU_FLEETFISSION_THRESHOLD"] = "16"
    t0 = time.time()
    stderr_tail: list = []

    def pump_stderr(pipe):
        # Stream the worker's progress() markers through live (a hung tier
        # must be diagnosable while it hangs), keeping a tail for the
        # artifact when the tier crashes.
        for line in pipe:
            print(line, end="", file=sys.stderr, flush=True)
            stderr_tail.append(line)
            del stderr_tail[:-40]
        pipe.close()

    p = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--tier", name],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    t = threading.Thread(target=pump_stderr, args=(p.stderr,), daemon=True)
    t.start()
    timed_out = threading.Event()

    def on_timeout():
        timed_out.set()
        p.kill()

    timer = threading.Timer(TIER_TIMEOUT_S[name], on_timeout)
    timer.start()
    try:
        out = p.stdout.read()
        p.wait()
    finally:
        timer.cancel()
    t.join(timeout=5)
    if timed_out.is_set():
        return {"status": "timeout", "wall_s": round(time.time() - t0, 1),
                "timeout_s": TIER_TIMEOUT_S[name]}
    wall = round(time.time() - t0, 1)
    for line in reversed(out.splitlines()):
        if line.startswith(RESULT_TAG):
            data = json.loads(line[len(RESULT_TAG):])
            return {"status": "ok", "wall_s": wall, **data}
    return {"status": "crashed", "wall_s": wall, "rc": p.returncode,
            "stderr_tail": "".join(stderr_tail)[-1500:]}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tier", choices=sorted(TIER_FNS))
    args = ap.parse_args()
    # Persistent XLA compile cache shared with the CLI and the checking
    # service: tier subprocesses re-use each other's compiles.
    from jepsen_tpu.ops.cache import init_compilation_cache
    init_compilation_cache(os.environ.get("JEPSEN_TPU_STORE", "store"))
    if args.tier:
        TIER_FNS[args.tier]()
        return 0

    tiers = {}
    # Easy (the headline) runs FIRST so later-tier failures can't starve it
    # of its time budget; cpu next (the denominator); the rest follow.
    for name in ("easy", "cpu", "hard", "ceiling", "refuted", "batch",
                 "batch_sweep", "ablation_on", "ablation_off", "setup2",
                 "sched", "multireg", "elle", "models", "fleet",
                 "procfleet", "obs"):
        progress(f"tier {name} (budget {TIER_TIMEOUT_S[name]}s)")
        tiers[name] = run_tier(name)
        progress(f"tier {name}: {tiers[name].get('status')} "
                 f"in {tiers[name].get('wall_s')}s")

    easy = tiers["easy"]
    wall = (statistics.median(easy["runs"])
            if easy.get("status") == "ok" else None)
    cpu10k = tiers["cpu"].get("10k") or {}
    cpu_wall = cpu10k.get("wall_s")
    vs_lower_bound = bool(cpu10k.get("timeout") or cpu10k.get("exploded_at"))

    # Full record — every tier verbatim, including stderr tails of crashed
    # tiers — goes to DISK; the one stdout line stays compact (<4 KB) so the
    # driver's tail always captures a parseable headline.  (Round-3 lesson:
    # a 1500-char traceback embedded in the line pushed the headline out of
    # the driver's 4 KB tail and the committed artifact was parsed: null.
    # The reference treats results as artifacts, not logs — store.clj
    # save-2!; this is the same discipline.)
    full = {
        "n_ops": N_OPS,
        "timing": "median-of-3",
        "tier_isolation": "per-tier subprocess + timeout",
        "chunk": "auto (1024: ghost-light 1-lane-state; else 512)",
        "analyzer": "wgl-tpu",
        "tiers": tiers,
    }
    full_path = os.environ.get(
        "JTPU_BENCH_FULL",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     # smoke runs must not clobber the committed hardware
                     # record
                     "bench_full_smoke.json" if SMOKE
                     else "bench_full.json"))
    try:
        with open(full_path, "w") as f:
            json.dump(full, f, indent=1)
    except OSError as e:  # a read-only fs must not cost the headline
        progress(f"could not write {full_path}: {e}")

    keep = ("status", "wall_s", "runs", "valid", "configs_explored",
            "max_capacity_reached", "histories_per_sec", "n_histories",
            "ops_each", "setup_s", "timeout_s", "rc", "subsume",
            "failed_op_index", "stream_fraction_to_refute",
            "degradation_timed", "window", "warm_s", "shakeout_s", "chunk",
            "device_vs_socket", "cpu_histories_per_sec_socket",
            "break_even_cores", "host_cores", "vs_cpu",
            "vs_cpu_is_lower_bound", "cpu")

    def slim(t: dict) -> dict:
        out = {k: t[k] for k in keep if t.get(k) is not None}
        if t.get("error"):
            out["error"] = str(t["error"])[:120]
        return out

    cpu_slim = {"status": tiers["cpu"].get("status")}
    for name in ("200", "1k", "10k", "hard"):
        if isinstance(tiers["cpu"].get(name), dict):
            cpu_slim[name] = {k: v for k, v in tiers["cpu"][name].items()
                              if k in ("wall_s", "valid", "timeout")}

    print(json.dumps({
        "metric": "cas_register_10k_op_linearizability_check_wall_s",
        "value": round(wall, 3) if wall else None,
        "unit": "s",
        "vs_baseline": (round(cpu_wall / wall, 2)
                        if wall and cpu_wall else None),
        "extra": {
            "n_ops": N_OPS,
            "timing": "median-of-3",
            "vs_baseline_is_lower_bound": vs_lower_bound,
            "vs_target_60s": round(TARGET_S / wall, 2) if wall else None,
            "cpu_baseline": cpu_slim,
            "easy": slim(easy),
            "hard": slim(tiers["hard"]),
            "ceiling": slim(tiers["ceiling"]),
            "refuted": slim(tiers["refuted"]),
            "batch": slim(tiers["batch"]),
            "ablation_on": slim(tiers["ablation_on"]),
            "ablation_off": slim(tiers["ablation_off"]),
            "second_process_setup": slim(tiers["setup2"]),
            "scheduler": {k: v for k, v in tiers["sched"].items()
                          if k not in ("status",)},
            "multireg": slim(tiers["multireg"]),
            "elle": {k: v for k, v in tiers["elle"].items()
                     if k in ("status", "wall_s", "n_histories", "ops_each",
                              "n_refuted", "histories_per_sec",
                              "cpu_histories_per_sec_socket",
                              "device_vs_socket", "break_even_cores",
                              "host_cores", "analyzer")},
            "fleet": {k: v for k, v in tiers["fleet"].items()
                      if k in ("status", "wall_s", "n_histories",
                               "solo_s", "fleet_s", "fleet_overhead",
                               "kill_recovery_s", "rerouted", "hedges",
                               "worker_failures")},
            "obs": {k: v for k, v in tiers["obs"].items()
                    if k in ("status", "wall_s", "n_histories",
                             "recorder_off_s", "recorder_on_s",
                             "recorder_overhead", "events_recorded",
                             "edges")},
            "batch_vs_cpu_socket": (tiers["batch"].get("shapes") or {}).get(
                "512", {}),
            "batch_sweep": {
                "status": tiers["batch_sweep"].get("status"),
                **(tiers["batch_sweep"].get("sweep") or {})},
            "full_record": os.path.basename(full_path),
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
